"""Native C++ core differential tests: every kernel must agree exactly with
its pure-python counterpart, and the library must be optional."""

import numpy as np
import pyarrow as pa
import pytest

from lakesoul_tpu import native
from lakesoul_tpu.utils import spark_hash as sh


pytestmark = pytest.mark.skipif(
    not native.available(), reason="native library unavailable (no compiler)"
)


class TestNativeHash:
    def test_i64_matches_python(self):
        rng = np.random.default_rng(0)
        vals = rng.integers(-(2**62), 2**62, 1000, dtype=np.int64)
        out = np.zeros(1000, dtype=np.uint32)
        native.hash_i64(vals, None, None, out, sh.HASH_SEED)
        expect = sh.hash_long_array(vals)
        np.testing.assert_array_equal(out, expect)

    def test_i32_with_seeds(self):
        rng = np.random.default_rng(1)
        vals = rng.integers(-(2**31), 2**31, 500, dtype=np.int32)
        seeds = rng.integers(0, 2**32, 500, dtype=np.uint32)
        out = np.zeros(500, dtype=np.uint32)
        native.hash_i32(vals, seeds, None, out, sh.HASH_SEED)
        expect = sh.hash_int_array(vals, seeds)
        np.testing.assert_array_equal(out, expect)

    def test_string_arrays_match_python_fallback(self, monkeypatch):
        vals = ["", "a", "hello world", "ab", "x" * 100, "日本語テキスト"]
        arr = pa.array(vals)
        got = sh.hash_array(arr)
        # force the python path and compare
        monkeypatch.setenv("LAKESOUL_TPU_DISABLE_NATIVE", "1")
        bufs = [v.encode("utf-8") for v in vals]
        expect = sh.hash_bytes_list(bufs)
        np.testing.assert_array_equal(got, expect)

    def test_sliced_string_array(self):
        arr = pa.array(["aa", "bb", "cc", "dd"]).slice(1, 2)
        got = sh.hash_array(arr)
        expect = sh.hash_bytes_list([b"bb", b"cc"])
        np.testing.assert_array_equal(got, expect)


class TestNativeMerge:
    def test_loser_tree_matches_sorted_merge(self):
        rng = np.random.default_rng(0)
        runs = []
        for _ in range(5):
            n = int(rng.integers(1, 200))
            runs.append(np.sort(rng.choice(500, n, replace=False)).astype(np.int64))
        keys = np.concatenate(runs)
        offsets = np.concatenate([[0], np.cumsum([len(r) for r in runs])]).astype(np.int64)
        order, tail, groups = native.merge_sorted_runs_i64(keys, offsets)
        merged = keys[order]
        assert np.all(np.diff(merged) >= 0)  # globally sorted
        # ties keep run order: for each key group the last element comes from
        # the highest run index containing it
        last_keys = merged[tail]
        assert groups == len(np.unique(keys))
        assert np.array_equal(np.unique(keys), np.sort(last_keys))
        # last-per-group row index must come from the newest run with that key
        for key in np.unique(keys):
            holders = [r for r in range(5) if key in runs[r]]
            newest = holders[-1]
            pos = int(np.nonzero((merged == key) & tail)[0][0])
            src_row = order[pos]
            assert offsets[newest] <= src_row < offsets[newest + 1]

    def test_merge_fast_path_equals_vectorized(self):
        from lakesoul_tpu.io.merge import merge_sorted_tables

        t1 = pa.table({"id": [1, 2, 3, 7], "v": [1.0, 2.0, 3.0, 7.0]})
        t2 = pa.table({"id": [2, 5], "v": [20.0, 50.0]})
        t3 = pa.table({"id": [3, 7, 9], "v": [30.0, 70.0, 90.0]})
        fast = merge_sorted_tables([t1, t2, t3], ["id"])
        import os

        os.environ["LAKESOUL_TPU_DISABLE_NATIVE"] = "1"
        try:
            # force re-evaluation without native (availability is cached, so
            # call the slow path directly by breaking the precondition)
            slow = merge_sorted_tables(
                [t1, t2, t3], ["id"], merge_operators={"v": "UseLast"}
            )
        finally:
            del os.environ["LAKESOUL_TPU_DISABLE_NATIVE"]
        assert fast.column("id").to_pylist() == [1, 2, 3, 5, 7, 9]
        assert fast.column("v").to_pylist() == slow.column("v").to_pylist()

    def test_empty_and_single_run(self):
        order, tail, groups = native.merge_sorted_runs_i64(
            np.array([1, 2, 3], dtype=np.int64), np.array([0, 3], dtype=np.int64)
        )
        assert list(order) == [0, 1, 2] and groups == 3
        assert list(tail) == [True, True, True]
        order, tail, groups = native.merge_sorted_runs_i64(
            np.zeros(0, dtype=np.int64), np.array([0, 0], dtype=np.int64)
        )
        assert len(order) == 0 and groups == 0


class TestNativePackBits:
    def test_matches_numpy_packbits(self):
        rng = np.random.default_rng(0)
        for d in (8, 13, 64, 100):
            bits = (rng.random((20, d)) > 0.5).astype(np.uint8)
            np.testing.assert_array_equal(
                native.pack_bits(bits), np.packbits(bits, axis=-1)
            )


class TestNativeEdgeCases:
    def test_int64_max_key_falls_back_correctly(self):
        # INT64_MAX is the C++ sentinel: the fast path must refuse it
        from lakesoul_tpu.io.merge import merge_sorted_tables

        big = np.iinfo(np.int64).max
        t1 = pa.table({"id": np.array([1, big], dtype=np.int64), "v": [1.0, 2.0]})
        t2 = pa.table({"id": np.array([big], dtype=np.int64), "v": [99.0]})
        m = merge_sorted_tables([t1, t2], ["id"])
        assert m.column("id").to_pylist() == [1, big]
        assert m.column("v").to_pylist() == [1.0, 99.0]

    def test_uint64_pk_not_reinterpreted(self):
        from lakesoul_tpu.io.merge import merge_sorted_tables

        t1 = pa.table({"id": pa.array([2**63 + 1], type=pa.uint64()), "v": [1.0]})
        t2 = pa.table({"id": pa.array([10], type=pa.uint64()), "v": [2.0]})
        m = merge_sorted_tables([t1, t2], ["id"])
        assert m.column("id").to_pylist() == [10, 2**63 + 1]  # unsigned order


class TestNativeWiring:
    def test_disable_env_honored_after_load(self, monkeypatch):
        assert native.available()
        monkeypatch.setenv("LAKESOUL_TPU_DISABLE_NATIVE", "1")
        assert not native.available()
        # python fallback still produces identical hashes
        vals = np.array([1, -5, 2**40], dtype=np.int64)
        h_py = sh.hash_long_array(vals)
        monkeypatch.delenv("LAKESOUL_TPU_DISABLE_NATIVE")
        h_nat = sh.hash_long_array(vals)
        np.testing.assert_array_equal(h_py, h_nat)

    def test_int_hash_native_vs_python_fallback(self, monkeypatch):
        rng = np.random.default_rng(3)
        small = rng.integers(-100, 100, 200, dtype=np.int16)
        u32 = rng.integers(0, 2**32, 200, dtype=np.uint32)
        u64 = rng.integers(0, 2**64, 200, dtype=np.uint64)
        native_hashes = [
            sh.hash_int_array(small), sh.hash_int_array(u32), sh.hash_long_array(u64)
        ]
        monkeypatch.setenv("LAKESOUL_TPU_DISABLE_NATIVE", "1")
        py_hashes = [
            sh.hash_int_array(small), sh.hash_int_array(u32), sh.hash_long_array(u64)
        ]
        for a, b in zip(native_hashes, py_hashes):
            np.testing.assert_array_equal(a, b)

    def test_pack_bits_nonbinary_input_matches_numpy(self):
        arr = np.array([[2, 0, 1, 0, 7, 0, 0, 0]], dtype=np.uint8)
        np.testing.assert_array_equal(native.pack_bits(arr), np.packbits(arr, axis=-1))
