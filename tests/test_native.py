"""Native C++ core differential tests: every kernel must agree exactly with
its pure-python counterpart, and the library must be optional."""

import numpy as np
import pyarrow as pa
import pytest

from lakesoul_tpu import native
from lakesoul_tpu.utils import spark_hash as sh


pytestmark = pytest.mark.skipif(
    not native.available(), reason="native library unavailable (no compiler)"
)


class TestNativeHash:
    def test_i64_matches_python(self):
        rng = np.random.default_rng(0)
        vals = rng.integers(-(2**62), 2**62, 1000, dtype=np.int64)
        out = np.zeros(1000, dtype=np.uint32)
        native.hash_i64(vals, None, None, out, sh.HASH_SEED)
        expect = sh.hash_long_array(vals)
        np.testing.assert_array_equal(out, expect)

    def test_i32_with_seeds(self):
        rng = np.random.default_rng(1)
        vals = rng.integers(-(2**31), 2**31, 500, dtype=np.int32)
        seeds = rng.integers(0, 2**32, 500, dtype=np.uint32)
        out = np.zeros(500, dtype=np.uint32)
        native.hash_i32(vals, seeds, None, out, sh.HASH_SEED)
        expect = sh.hash_int_array(vals, seeds)
        np.testing.assert_array_equal(out, expect)

    def test_string_arrays_match_python_fallback(self, monkeypatch):
        vals = ["", "a", "hello world", "ab", "x" * 100, "日本語テキスト"]
        arr = pa.array(vals)
        got = sh.hash_array(arr)
        # force the python path and compare
        monkeypatch.setenv("LAKESOUL_TPU_DISABLE_NATIVE", "1")
        bufs = [v.encode("utf-8") for v in vals]
        expect = sh.hash_bytes_list(bufs)
        np.testing.assert_array_equal(got, expect)

    def test_sliced_string_array(self):
        arr = pa.array(["aa", "bb", "cc", "dd"]).slice(1, 2)
        got = sh.hash_array(arr)
        expect = sh.hash_bytes_list([b"bb", b"cc"])
        np.testing.assert_array_equal(got, expect)


class TestNativeMerge:
    def test_loser_tree_matches_sorted_merge(self):
        rng = np.random.default_rng(0)
        runs = []
        for _ in range(5):
            n = int(rng.integers(1, 200))
            runs.append(np.sort(rng.choice(500, n, replace=False)).astype(np.int64))
        keys = np.concatenate(runs)
        offsets = np.concatenate([[0], np.cumsum([len(r) for r in runs])]).astype(np.int64)
        order, tail, groups = native.merge_sorted_runs_i64(keys, offsets)
        merged = keys[order]
        assert np.all(np.diff(merged) >= 0)  # globally sorted
        # ties keep run order: for each key group the last element comes from
        # the highest run index containing it
        last_keys = merged[tail]
        assert groups == len(np.unique(keys))
        assert np.array_equal(np.unique(keys), np.sort(last_keys))
        # last-per-group row index must come from the newest run with that key
        for key in np.unique(keys):
            holders = [r for r in range(5) if key in runs[r]]
            newest = holders[-1]
            pos = int(np.nonzero((merged == key) & tail)[0][0])
            src_row = order[pos]
            assert offsets[newest] <= src_row < offsets[newest + 1]

    def test_merge_fast_path_equals_vectorized(self):
        from lakesoul_tpu.io.merge import merge_sorted_tables

        t1 = pa.table({"id": [1, 2, 3, 7], "v": [1.0, 2.0, 3.0, 7.0]})
        t2 = pa.table({"id": [2, 5], "v": [20.0, 50.0]})
        t3 = pa.table({"id": [3, 7, 9], "v": [30.0, 70.0, 90.0]})
        fast = merge_sorted_tables([t1, t2, t3], ["id"])
        import os

        os.environ["LAKESOUL_TPU_DISABLE_NATIVE"] = "1"
        try:
            # force re-evaluation without native (availability is cached, so
            # call the slow path directly by breaking the precondition)
            slow = merge_sorted_tables(
                [t1, t2, t3], ["id"], merge_operators={"v": "UseLast"}
            )
        finally:
            del os.environ["LAKESOUL_TPU_DISABLE_NATIVE"]
        assert fast.column("id").to_pylist() == [1, 2, 3, 5, 7, 9]
        assert fast.column("v").to_pylist() == slow.column("v").to_pylist()

    def test_empty_and_single_run(self):
        order, tail, groups = native.merge_sorted_runs_i64(
            np.array([1, 2, 3], dtype=np.int64), np.array([0, 3], dtype=np.int64)
        )
        assert list(order) == [0, 1, 2] and groups == 3
        assert list(tail) == [True, True, True]
        order, tail, groups = native.merge_sorted_runs_i64(
            np.zeros(0, dtype=np.int64), np.array([0, 0], dtype=np.int64)
        )
        assert len(order) == 0 and groups == 0


class TestNativePackBits:
    def test_matches_numpy_packbits(self):
        rng = np.random.default_rng(0)
        for d in (8, 13, 64, 100):
            bits = (rng.random((20, d)) > 0.5).astype(np.uint8)
            np.testing.assert_array_equal(
                native.pack_bits(bits), np.packbits(bits, axis=-1)
            )


class TestNativeEdgeCases:
    def test_int64_max_key_falls_back_correctly(self):
        # INT64_MAX is the C++ sentinel: the fast path must refuse it
        from lakesoul_tpu.io.merge import merge_sorted_tables

        big = np.iinfo(np.int64).max
        t1 = pa.table({"id": np.array([1, big], dtype=np.int64), "v": [1.0, 2.0]})
        t2 = pa.table({"id": np.array([big], dtype=np.int64), "v": [99.0]})
        m = merge_sorted_tables([t1, t2], ["id"])
        assert m.column("id").to_pylist() == [1, big]
        assert m.column("v").to_pylist() == [1.0, 99.0]

    def test_uint64_pk_not_reinterpreted(self):
        from lakesoul_tpu.io.merge import merge_sorted_tables

        t1 = pa.table({"id": pa.array([2**63 + 1], type=pa.uint64()), "v": [1.0]})
        t2 = pa.table({"id": pa.array([10], type=pa.uint64()), "v": [2.0]})
        m = merge_sorted_tables([t1, t2], ["id"])
        assert m.column("id").to_pylist() == [10, 2**63 + 1]  # unsigned order


class TestNativeWiring:
    def test_disable_env_honored_after_load(self, monkeypatch):
        assert native.available()
        monkeypatch.setenv("LAKESOUL_TPU_DISABLE_NATIVE", "1")
        assert not native.available()
        # python fallback still produces identical hashes
        vals = np.array([1, -5, 2**40], dtype=np.int64)
        h_py = sh.hash_long_array(vals)
        monkeypatch.delenv("LAKESOUL_TPU_DISABLE_NATIVE")
        h_nat = sh.hash_long_array(vals)
        np.testing.assert_array_equal(h_py, h_nat)

    def test_int_hash_native_vs_python_fallback(self, monkeypatch):
        rng = np.random.default_rng(3)
        small = rng.integers(-100, 100, 200, dtype=np.int16)
        u32 = rng.integers(0, 2**32, 200, dtype=np.uint32)
        u64 = rng.integers(0, 2**64, 200, dtype=np.uint64)
        native_hashes = [
            sh.hash_int_array(small), sh.hash_int_array(u32), sh.hash_long_array(u64)
        ]
        monkeypatch.setenv("LAKESOUL_TPU_DISABLE_NATIVE", "1")
        py_hashes = [
            sh.hash_int_array(small), sh.hash_int_array(u32), sh.hash_long_array(u64)
        ]
        for a, b in zip(native_hashes, py_hashes):
            np.testing.assert_array_equal(a, b)

    def test_pack_bits_nonbinary_input_matches_numpy(self):
        arr = np.array([[2, 0, 1, 0, 7, 0, 0, 0]], dtype=np.uint8)
        np.testing.assert_array_equal(native.pack_bits(arr), np.packbits(arr, axis=-1))


class TestNativeBytesMerge:
    """String/binary PK loser tree (r2: the fast path no longer covers only
    int64 keys — reference v2 merges any key shape)."""

    def test_bytes_merge_matches_sorted(self):
        import numpy as np
        import pyarrow as pa

        from lakesoul_tpu import native
        from lakesoul_tpu.io.merge import _arrow_bytes_layout

        if not native.available():
            pytest.skip("native lib unavailable")
        rng = np.random.default_rng(0)
        runs = []
        for _ in range(5):
            n = int(rng.integers(1, 50))
            vals = sorted(
                "".join(rng.choice(list("abcdef"), rng.integers(0, 6)))
                for _ in range(n)
            )
            runs.append(pa.array(vals, type=pa.string()))
        big = pa.concat_arrays(runs)
        data, offsets = _arrow_bytes_layout(big)
        run_offsets = np.concatenate([[0], np.cumsum([len(r) for r in runs])]).astype(np.int64)
        order, tail, groups = native.merge_sorted_runs_bytes(data, offsets, run_offsets)
        merged = [big[int(i)].as_py() for i in order]
        assert merged == sorted(big.to_pylist())
        assert groups == len(set(big.to_pylist()))
        # ties resolve to the LAST (newest) run's row
        last = order[tail]
        seen = {}
        starts = run_offsets
        for idx in last:
            run_id = int(np.searchsorted(starts, idx, side="right") - 1)
            key = big[int(idx)].as_py()
            for r in range(run_id + 1, len(runs)):
                assert key not in set(runs[r].to_pylist()), (
                    f"{key!r} surviving from run {run_id} but newer run {r} has it"
                )

    def test_string_pk_fast_path_equals_fallback(self, monkeypatch):
        import numpy as np
        import pyarrow as pa

        from lakesoul_tpu.io.merge import merge_sorted_tables

        rng = np.random.default_rng(1)
        tables = []
        for w in range(3):
            n = 200
            keys = sorted(f"k{int(x):04d}" for x in rng.integers(0, 300, n))
            tables.append(pa.table({"k": keys, "v": rng.normal(size=n)}))
        fast = merge_sorted_tables(tables, ["k"])
        monkeypatch.setenv("LAKESOUL_TPU_DISABLE_NATIVE", "1")
        slow = merge_sorted_tables(tables, ["k"])
        assert fast.equals(slow)

    def test_string_pk_through_table_api(self, tmp_warehouse):
        import numpy as np
        import pyarrow as pa

        from lakesoul_tpu import LakeSoulCatalog

        catalog = LakeSoulCatalog(str(tmp_warehouse))
        schema = pa.schema([("name", pa.string()), ("v", pa.float64())])
        t = catalog.create_table("strpk", schema, primary_keys=["name"], hash_bucket_num=2)
        t.write_arrow(pa.table({"name": [f"u{i}" for i in range(100)],
                                "v": np.arange(100, dtype=np.float64)}))
        t.upsert(pa.table({"name": ["u3", "u42"], "v": [300.0, 420.0]}))
        got = t.to_arrow().sort_by("name")
        assert got.num_rows == 100
        vals = dict(zip(got.column("name").to_pylist(), got.column("v").to_pylist()))
        assert vals["u3"] == 300.0 and vals["u42"] == 420.0 and vals["u50"] == 50.0


class TestCompositeMerge:
    """Composite fixed-width PKs through the byte loser tree (memcomparable
    encoding: big-endian, sign-flip ints, IEEE order-flip floats)."""

    def _merged_pair(self, tables, pks, monkeypatch):
        from lakesoul_tpu.io.merge import merge_sorted_tables

        fast = merge_sorted_tables(tables, pks)
        monkeypatch.setenv("LAKESOUL_TPU_DISABLE_NATIVE", "1")
        slow = merge_sorted_tables(tables, pks)
        monkeypatch.delenv("LAKESOUL_TPU_DISABLE_NATIVE")
        return fast, slow

    def test_int_float_composite_equals_fallback(self, monkeypatch):
        import numpy as np
        import pyarrow as pa
        import pyarrow.compute as pc

        rng = np.random.default_rng(0)
        tables = []
        for _ in range(4):
            n = 300
            t = pa.table(
                {
                    "a": rng.integers(-20, 20, n).astype(np.int32),
                    "b": np.round(rng.normal(size=n), 1),  # dup-friendly
                    "v": rng.integers(0, 9, n),
                }
            )
            idx = pc.sort_indices(t, sort_keys=[("a", "ascending"), ("b", "ascending")])
            tables.append(t.take(idx))
        fast, slow = self._merged_pair(tables, ["a", "b"], monkeypatch)
        assert fast.equals(slow)

    def test_negative_floats_and_sign_flip(self, monkeypatch):
        import pyarrow as pa

        t1 = pa.table({"x": pa.array([-3.5, -1.0, 0.0, 2.5]),
                       "y": pa.array([1, 2, 3, 4], type=pa.int16()), "v": [1, 2, 3, 4]})
        t2 = pa.table({"x": pa.array([-3.5, 2.5]),
                       "y": pa.array([1, 4], type=pa.int16()), "v": [10, 40]})
        fast, slow = self._merged_pair([t1, t2], ["x", "y"], monkeypatch)
        assert fast.equals(slow)
        assert fast.column("v").to_pylist() == [10, 2, 3, 40]  # newest wins

    def test_nan_keys_fall_back(self, monkeypatch):
        import numpy as np
        import pyarrow as pa

        t1 = pa.table({"x": pa.array([1.0, float("nan")]), "y": [1, 2], "v": [1, 2]})
        fast, slow = self._merged_pair([t1], ["x", "y"], monkeypatch)
        # NaN != NaN defeats Table.equals; compare arrays NaN-aware
        np.testing.assert_array_equal(
            fast.column("x").to_numpy(), slow.column("x").to_numpy()
        )
        assert fast.column("v").to_pylist() == slow.column("v").to_pylist()

    def test_composite_through_table_api(self, tmp_warehouse):
        import numpy as np
        import pyarrow as pa

        from lakesoul_tpu import LakeSoulCatalog

        catalog = LakeSoulCatalog(str(tmp_warehouse))
        schema = pa.schema([("day", pa.int32()), ("slot", pa.int64()), ("v", pa.float64())])
        t = catalog.create_table("cpk", schema, primary_keys=["day", "slot"], hash_bucket_num=2)
        t.write_arrow(pa.table({
            "day": np.repeat(np.arange(5, dtype=np.int32), 20),
            "slot": np.tile(np.arange(20, dtype=np.int64), 5),
            "v": np.zeros(100),
        }))
        t.upsert(pa.table({"day": pa.array([2], type=pa.int32()),
                           "slot": pa.array([7], type=pa.int64()), "v": [9.0]}))
        import pyarrow.compute as pc

        got = t.to_arrow()
        assert got.num_rows == 100
        sel = got.filter(pc.and_(pc.equal(got["day"], 2), pc.equal(got["slot"], 7)))
        assert sel.column("v").to_pylist() == [9.0]


class TestNativeGather:
    """ls_gather_fixed / ls_gather_valid_bits: the merge-apply gather+fill
    entry point must agree exactly with pyarrow take (+ if_else null fill)."""

    def _table(self, n=500, seed=0):
        rng = np.random.default_rng(seed)
        return pa.table({
            "i64": pa.array(rng.integers(-(2**60), 2**60, n, dtype=np.int64)),
            "i32": pa.array(rng.integers(-(2**30), 2**30, n).astype(np.int32)),
            "i16": pa.array(rng.integers(-1000, 1000, n).astype(np.int16)),
            "u8": pa.array(rng.integers(0, 255, n).astype(np.uint8)),
            "f32": pa.array(rng.normal(size=n).astype(np.float32)),
            "f64": pa.array(rng.normal(size=n)),
            "ts": pa.array(rng.integers(0, 10**15, n).astype("datetime64[us]")),
            "s": pa.array([f"row{i}" for i in range(n)]),
            "nv": pa.array(
                [None if i % 5 == 0 else float(i) for i in range(n)],
                type=pa.float64(),
            ),
        })

    def test_take_indices_matches_pyarrow_take(self):
        from lakesoul_tpu.io.merge import take_indices

        t = self._table()
        rng = np.random.default_rng(1)
        idx = rng.integers(0, len(t), 300).astype(np.int64)
        ref = t.take(pa.array(idx))
        got = take_indices(t, idx)
        assert got.equals(ref)

    def test_take_indices_on_sliced_chunks(self):
        from lakesoul_tpu.io.merge import take_indices

        t = self._table().slice(37, 400)  # nonzero offsets in every buffer
        rng = np.random.default_rng(2)
        idx = rng.integers(0, len(t), 200).astype(np.int64)
        assert take_indices(t, idx).equals(t.take(pa.array(idx)))

    def test_gather_fill_negative_index_is_null(self):
        from lakesoul_tpu.io.merge import _gather_fill

        col = pa.array(np.arange(50, dtype=np.int64))
        idx = np.array([0, -1, 3, -1, 49], dtype=np.int64)
        out = _gather_fill(pa.chunked_array([col]), idx)
        assert out.to_pylist() == [0, None, 3, None, 49]
        # and over a column that already has nulls
        coln = pa.array([None if i % 3 == 0 else i for i in range(50)],
                        type=pa.int64())
        outn = _gather_fill(pa.chunked_array([coln]), idx)
        assert outn.to_pylist() == [None, None, None, None, 49]

    def test_gather_fill_matches_python_fallback(self, monkeypatch):
        from lakesoul_tpu.io.merge import _gather_fill

        rng = np.random.default_rng(3)
        col = pa.chunked_array([pa.array(
            [None if i % 7 == 0 else float(i) for i in range(200)],
            type=pa.float64(),
        )])
        idx = rng.integers(-1, 200, 120).astype(np.int64)
        fast = _gather_fill(col, idx)
        monkeypatch.setenv("LAKESOUL_TPU_DISABLE_NATIVE", "1")
        slow = _gather_fill(col, idx)
        monkeypatch.delenv("LAKESOUL_TPU_DISABLE_NATIVE")
        assert pa.chunked_array([fast]).equals(pa.chunked_array(
            [slow] if isinstance(slow, pa.Array) else slow.chunks
        ))

    def test_gather_valid_bits_counts(self):
        vals = pa.array([None, 1, 2, None, 4], type=pa.int64())
        bufs = vals.buffers()
        vbits = np.frombuffer(bufs[0], dtype=np.uint8)
        idx = np.array([1, 0, -1, 4], dtype=np.int64)
        out, nulls = native.gather_valid_bits(vbits, vals.offset, idx)
        assert nulls == 2  # index 0 (null source) + index -1 (fill)
        got = [(out[i >> 3] >> (i & 7)) & 1 for i in range(4)]
        assert got == [1, 0, 0, 1]

    def test_empty_and_identity(self):
        from lakesoul_tpu.io.merge import take_indices

        t = self._table(20)
        assert len(take_indices(t, np.array([], dtype=np.int64))) == 0
        ident = take_indices(t, np.arange(20, dtype=np.int64))
        assert ident.equals(t)

    def test_take_indices_negative_fill_on_chunked_null_free(self):
        """Negative indices must yield NULL cells even for null-free
        multi-chunk columns — the multi-column fast path cannot represent
        fill rows (searchsorted would map -1 to garbage), so their presence
        must route every column through the per-column gather+fill."""
        from lakesoul_tpu.io.merge import take_indices

        a = pa.table({"x": pa.array(np.arange(10, dtype=np.int64)),
                      "y": pa.array(np.arange(10).astype(np.float32))})
        b = pa.table({"x": pa.array(np.arange(10, 25, dtype=np.int64)),
                      "y": pa.array(np.arange(10, 25).astype(np.float32))})
        t = pa.concat_tables([a, b])  # 2 chunks per column, no nulls
        idx = np.array([3, -1, 14, -1, 24], dtype=np.int64)
        got = take_indices(t, idx)
        assert got.column("x").to_pylist() == [3, None, 14, None, 24]
        assert got.column("y").to_pylist() == [3.0, None, 14.0, None, 24.0]
