"""Unified observability layer (obs/): registry thread-safety, histogram
bucket edges, span nesting + trace-id propagation over a live Flight round
trip, loader rows/sec counters, and the single /metrics endpoint."""

import threading
import urllib.request

import pyarrow as pa
import pytest

from lakesoul_tpu import LakeSoulCatalog
from lakesoul_tpu.obs import (
    MetricsRegistry,
    current_trace_id,
    recent_spans,
    registry,
    sanitize_trace_id,
    span,
)

SCHEMA = pa.schema([("id", pa.int64()), ("v", pa.float64())])


@pytest.fixture()
def catalog(tmp_warehouse):
    return LakeSoulCatalog(str(tmp_warehouse))


class TestRegistry:
    def test_counter_thread_safety(self):
        reg = MetricsRegistry()
        c = reg.counter("lakesoul_test_inc_total")

        def work():
            for _ in range(10_000):
                c.inc()

        threads = [threading.Thread(target=work) for _ in range(8)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        assert c.value == 80_000

    def test_metrics_memoized_per_name_and_labels(self):
        reg = MetricsRegistry()
        a = reg.counter("x_total", op="a")
        assert reg.counter("x_total", op="a") is a
        assert reg.counter("x_total", op="b") is not a
        # a name is permanently bound to its first kind
        with pytest.raises(ValueError, match="already registered"):
            reg.gauge("x_total")

    def test_histogram_bucket_edges(self):
        reg = MetricsRegistry()
        h = reg.histogram("h_seconds", buckets=(1.0, 5.0, 10.0))
        for v in (0.5, 1.0, 1.00001, 5.0, 42.0):
            h.observe(v)
        snap = h.value
        # Prometheus cumulative le semantics: bucket counts values <= bound
        assert snap["buckets"][1.0] == 2  # 0.5, 1.0 (edge value included)
        assert snap["buckets"][5.0] == 4  # + 1.00001, 5.0
        assert snap["buckets"][10.0] == 4
        assert snap["count"] == 5  # +Inf picks up 42.0
        assert snap["sum"] == pytest.approx(49.50001)
        text = reg.prometheus_text()
        assert "# TYPE h_seconds histogram" in text
        assert 'h_seconds_bucket{le="+Inf"} 5' in text
        assert "h_seconds_count 5" in text

    def test_histogram_bucket_mismatch_raises(self):
        reg = MetricsRegistry()
        reg.histogram("hb_seconds", buckets=(1.0, 5.0))
        assert reg.histogram("hb_seconds").bounds == (1.0, 5.0)  # no-arg OK
        with pytest.raises(ValueError, match="buckets"):
            reg.histogram("hb_seconds", buckets=(1.0, 10.0))

    def test_stream_counters_survive_instance_gc(self):
        import gc

        from lakesoul_tpu.obs.metrics import StreamMetrics, _collect_streams

        def total(samples):
            return {n: v for n, _k, v, _l in samples}["lakesoul_flight_rows_in"]

        before = total(_collect_streams())
        sm = StreamMetrics()
        sm.add(rows_in=11)
        assert total(_collect_streams()) == before + 11
        del sm
        gc.collect()
        # counters stay monotonic across instance churn (gauges drop)
        assert total(_collect_streams()) == before + 11

    def test_gauge_set_inc_dec_and_function(self):
        reg = MetricsRegistry()
        g = reg.gauge("g_depth")
        g.set(5)
        g.inc(2)
        g.dec()
        assert g.value == 6
        g2 = reg.gauge("g_sampled")
        g2.set_function(lambda: 7)
        assert reg.snapshot()["g_sampled"] == 7

    def test_collector_merged_into_exposition(self):
        reg = MetricsRegistry()
        reg.register_collector(lambda: [("ext_total", "counter", 3, {"src": "a"})])
        reg.register_collector(lambda: [("ext_total", "counter", 4, {"src": "a"})])
        snap = reg.snapshot()
        assert snap['ext_total{src="a"}'] == 7  # same series sums
        assert 'ext_total{src="a"} 7' in reg.prometheus_text()

    def test_broken_collector_does_not_break_exposition(self):
        reg = MetricsRegistry()
        reg.counter("ok_total").inc()

        def broken():
            raise RuntimeError("sampler died")

        reg.register_collector(broken)
        assert reg.snapshot()["ok_total"] == 1


class TestSpans:
    def test_nesting_and_trace_inheritance(self):
        assert current_trace_id() is None
        with span("obs-parent") as p:
            assert current_trace_id() == p.trace_id
            with span("obs-child") as c:
                assert c.trace_id == p.trace_id
                assert c.parent_id == p.span_id
        assert current_trace_id() is None
        got = recent_spans(name="obs-child", trace_id=p.trace_id)
        assert got and got[-1]["parent_id"] == p.span_id

    def test_explicit_trace_id_pins_the_trace(self):
        with span("a", trace_id="tid-outer"):
            with span("b", trace_id="tid-pinned") as b:
                assert b.trace_id == "tid-pinned"

    def test_duration_lands_in_registry_histogram(self):
        with span("obs-timed") as s:
            pass
        assert s.duration_s is not None and s.duration_s >= 0.0
        snap = registry().snapshot()
        key = 'lakesoul_span_seconds{name="obs-timed"}'
        assert snap[key]["count"] >= 1

    def test_sanitize_trace_id(self):
        assert sanitize_trace_id("ok-id_1.2") == "ok-id_1.2"
        assert sanitize_trace_id(b"abc") == "abc"
        assert sanitize_trace_id("") is None
        assert sanitize_trace_id("bad id") is None
        assert sanitize_trace_id("x" * 65) is None


class TestFlightTracePropagation:
    def test_client_supplied_trace_id_shows_in_server_spans(self, catalog):
        from lakesoul_tpu.service.flight import (
            LakeSoulFlightClient,
            LakeSoulFlightServer,
        )

        t = catalog.create_table("tr", SCHEMA)
        t.write_arrow(pa.table({"id": [1, 2], "v": [1.0, 2.0]}))
        server = LakeSoulFlightServer(catalog, "grpc://127.0.0.1:0")
        try:
            client = LakeSoulFlightClient(
                f"grpc://127.0.0.1:{server.port}", trace_id="feedbeef-042"
            )
            out = client.scan("tr")
            assert out.num_rows == 2
            client.action("metrics")
            names = {s["name"] for s in recent_spans(trace_id="feedbeef-042")}
            assert "flight.do_get" in names
            assert "flight.stream_get" in names  # the streamed delivery too
            assert "flight.do_action" in names
        finally:
            server.shutdown()

    def test_flight_sql_query_carries_trace_into_executor(self, catalog):
        import pyarrow.flight as flight

        from lakesoul_tpu.service.flight_sql import (
            LakeSoulFlightSqlServer,
            _pack,
            pb,
        )

        t = catalog.create_table("trsql", SCHEMA)
        t.write_arrow(pa.table({"id": [1], "v": [1.0]}))
        server = LakeSoulFlightSqlServer(catalog, "grpc://127.0.0.1:0")
        try:
            opts = flight.FlightCallOptions(
                headers=[(b"x-trace-id", b"sqltrace-7")]
            )
            client = flight.FlightClient(f"grpc://127.0.0.1:{server.port}")
            desc = flight.FlightDescriptor.for_command(
                _pack(pb.CommandStatementQuery(query="SELECT id FROM trsql"))
            )
            info = client.get_flight_info(desc, options=opts)
            client.do_get(info.endpoints[0].ticket, options=opts).read_all()
            names = {s["name"] for s in recent_spans(trace_id="sqltrace-7")}
            assert "flightsql.get_flight_info" in names
            assert "sql.execute" in names  # nested under the gateway span
        finally:
            server.shutdown()


class TestLoaderTelemetry:
    def test_rows_per_sec_queue_depth_and_epoch_totals(self, catalog):
        t = catalog.create_table("ld", SCHEMA)
        n = 100
        t.write_arrow(
            pa.table({"id": list(range(n)), "v": [float(i) for i in range(n)]})
        )
        before = registry().snapshot().get("lakesoul_loader_rows_total", 0)
        it = t.scan().batch_size(16).to_jax_iter(
            device_put=False, drop_remainder=False
        )
        rows = sum(len(b["id"]) for b in it)
        assert rows == n
        stats = it.stats()
        assert stats["rows"] == n
        assert stats["batches"] == 7  # 6 × 16 + tail
        assert stats["epochs"] == 1
        assert stats["epoch_rows"] == [n]
        assert stats["rows_per_sec"] > 0
        assert stats["batches_per_sec"] > 0
        assert stats["stall_s"] >= 0.0
        assert "queue_depth" in stats
        after = registry().snapshot()["lakesoul_loader_rows_total"]
        assert after - before == n

    def test_second_epoch_accumulates(self, catalog):
        t = catalog.create_table("ld2", SCHEMA)
        t.write_arrow(pa.table({"id": [1, 2, 3], "v": [1.0, 2.0, 3.0]}))
        it = t.scan().batch_size(2).to_jax_iter(
            device_put=False, drop_remainder=False
        )
        for _ in it:
            pass
        for _ in it:
            pass
        stats = it.stats()
        assert stats["epochs"] == 2
        assert stats["rows"] == 6
        assert stats["epoch_rows"] == [3, 3]

    def test_abandoned_epoch_is_not_counted_complete(self, catalog):
        t = catalog.create_table("ld3", SCHEMA)
        t.write_arrow(pa.table({"id": list(range(50)), "v": [0.0] * 50}))
        it = t.scan().batch_size(4).to_jax_iter(
            device_put=False, drop_remainder=False
        )
        for _ in it:
            break  # consumer abandons mid-epoch
        stats = it.stats()
        assert stats["epochs"] == 0
        assert stats["rows"] >= 4


class TestUnifiedMetricsEndpoint:
    def test_one_endpoint_serves_every_layer(self, catalog, tmp_path):
        """Acceptance: /metrics on a gateway process shows stream, cache,
        executor-latency, and loader series from ONE registry."""
        import fsspec

        from lakesoul_tpu.io.page_cache import DiskPageCache
        from lakesoul_tpu.obs import serve_prometheus
        from lakesoul_tpu.service.flight import LakeSoulFlightClient
        from lakesoul_tpu.service.flight_sql import LakeSoulFlightSqlServer

        t = catalog.create_table("obs_all", SCHEMA)
        t.write_arrow(pa.table({"id": [1, 2, 3], "v": [1.0, 2.0, 3.0]}))

        # page cache traffic
        fs = fsspec.filesystem("memory")
        fs.pipe_file("/obs/blob", b"z" * 2048)
        cache = DiskPageCache(str(tmp_path / "c"), page_bytes=512)
        cache.read_range(fs, "/obs/blob", 0, 2048)
        cache.read_range(fs, "/obs/blob", 0, 2048)

        # loader traffic
        for _ in t.scan().batch_size(2).to_jax_iter(
            device_put=False, drop_remainder=False
        ):
            pass

        server = LakeSoulFlightSqlServer(catalog, "grpc://127.0.0.1:0")
        srv = serve_prometheus(port=0, host="127.0.0.1")
        try:
            # gateway + executor traffic
            client = LakeSoulFlightClient(f"grpc://127.0.0.1:{server.port}")
            client.scan("obs_all")
            client.action("sql", {"statement": "SELECT id FROM obs_all"})

            port = srv.server_address[1]
            text = (
                urllib.request.urlopen(f"http://127.0.0.1:{port}/metrics")
                .read()
                .decode()
            )
            assert "lakesoul_flight_total_get_streams" in text  # streams
            assert "lakesoul_cache_hits_total" in text  # page cache
            assert "lakesoul_sql_stage_seconds_bucket" in text  # executor
            assert "lakesoul_loader_rows_total" in text  # loader
            assert "lakesoul_io_scan_unit_seconds_bucket" in text  # io
            assert "lakesoul_meta_commits_total" in text  # meta commits
        finally:
            srv.shutdown()
            server.shutdown()
            fs.rm("/obs", recursive=True)
        # PIN (boundedness pack): the exporter's serve thread is named and
        # shutdown() joins it — not an anonymous daemon nothing can reap
        assert srv._serve_thread.name == "lakesoul-metrics-exporter"
        assert not srv._serve_thread.is_alive()

    def test_obs_stats_console_command(self, catalog):
        from lakesoul_tpu.service.console import Console

        console = Console(catalog)
        t = catalog.create_table("obs_c", SCHEMA)
        t.write_arrow(pa.table({"id": [1], "v": [1.0]}))
        out = console.execute("obs-stats lakesoul_meta")
        assert "lakesoul_meta_commits_total" in out
        cache_out = console.execute("cache-stats")
        assert "hits=" in cache_out and "hit_rate=" in cache_out
