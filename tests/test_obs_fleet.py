"""Fleet observability plane: cross-process aggregation, traces, postmortems.

Tier-1 legs exercise the whole plane in-process against real spool files
(the publisher's fsync+rename output IS the wire format); the slow leg
SIGKILLs a REAL scanplane worker mid-range and recovers its flight
recorder + last snapshot from the spool — the crash-postmortem acceptance.
"""

from __future__ import annotations

import json
import os
import time
import urllib.error
import urllib.request

import pytest

from lakesoul_tpu.obs import fleet
from lakesoul_tpu.obs.exporter import serve_prometheus
from lakesoul_tpu.obs.metrics import (
    Histogram,
    MetricsRegistry,
    parse_series_key,
    registry,
)


@pytest.fixture()
def spool(tmp_path):
    d = tmp_path / "obs-spool"
    d.mkdir()
    return str(d)


def _member(
    spool_dir,
    *,
    role,
    service_id,
    snapshot,
    kinds=None,
    heartbeat_unix=None,
    started_unix=None,
    chips=0,
    host="h1",
    pid=1234,
):
    now = time.time()
    doc = {
        "role": role,
        "service_id": service_id,
        "pid": pid,
        "host": host,
        "started_unix": now - 10.0 if started_unix is None else started_unix,
        "heartbeat_unix": now if heartbeat_unix is None else heartbeat_unix,
        "chips": chips,
        "kinds": kinds or {},
        "snapshot": snapshot,
    }
    with open(os.path.join(spool_dir, f"member-{service_id}.json"), "w") as f:
        json.dump(doc, f)
    return doc


def _recorder(spool_dir, *, role, service_id, events=(), spans=(), pid=1234):
    doc = {
        "role": role,
        "service_id": service_id,
        "pid": pid,
        "heartbeat_unix": time.time(),
        "reason": "test",
        "events": list(events),
        "spans": list(spans),
    }
    with open(os.path.join(spool_dir, f"recorder-{service_id}.json"), "w") as f:
        json.dump(doc, f)
    return doc


# ------------------------------------------------------------ wire format


class TestSeriesKeyParsing:
    def test_round_trips_snapshot_keys(self):
        reg = MetricsRegistry()
        reg.counter("lakesoul_x_total", stage="decode", worker="w-1").inc(3)
        reg.gauge("lakesoul_x_depth").set(7)
        for key in reg.snapshot():
            name, labels = parse_series_key(key)
            assert name is not None
        name, labels = parse_series_key(
            'lakesoul_x_total{stage="decode",worker="w-1"}'
        )
        assert name == "lakesoul_x_total"
        assert labels == {"stage": "decode", "worker": "w-1"}
        assert parse_series_key("lakesoul_plain") == ("lakesoul_plain", {})
        assert parse_series_key("{broken") == (None, None)


class TestHistogramMergeDist:
    def test_same_grid_is_exact(self):
        src = Histogram("h", buckets=(0.1, 1.0, 10.0))
        for v in (0.05, 0.5, 0.5, 5.0, 50.0):
            src.observe(v)
        dst = Histogram("h", buckets=(0.1, 1.0, 10.0))
        sv = src.value
        dst.merge_dist(sv["buckets"], sv["sum"], sv["count"])
        assert dst.value == sv

    def test_json_string_bounds_and_coarser_grid(self):
        src = Histogram("h", buckets=(0.1, 1.0, 10.0))
        for v in (0.05, 0.5, 5.0, 50.0):
            src.observe(v)
        # a JSON round trip turns bucket bounds into strings
        wire = json.loads(json.dumps(src.value))
        dst = Histogram("h", buckets=(1.0, 10.0))
        dst.merge_dist(wire["buckets"], wire["sum"], wire["count"])
        v = dst.value
        assert v["count"] == 4 and v["sum"] == pytest.approx(wire["sum"])
        # <=0.1 and <=1.0 both land in the <=1.0 bucket; 50.0 rides +Inf
        assert v["buckets"][1.0] == 2
        assert v["buckets"][10.0] == 3


class TestMergeSnapshot:
    def test_counters_sum_gauges_keep_identity_histograms_merge(self):
        a = MetricsRegistry()
        a.counter("lakesoul_w_rows_total").inc(100)
        a.gauge("lakesoul_w_depth").set(3)
        a.histogram("lakesoul_w_seconds", buckets=(1.0, 10.0)).observe(0.5)
        b = MetricsRegistry()
        b.counter("lakesoul_w_rows_total").inc(40)
        b.gauge("lakesoul_w_depth").set(9)
        b.histogram("lakesoul_w_seconds", buckets=(1.0, 10.0)).observe(5.0)

        out = MetricsRegistry()
        for reg, sid in ((a, "p1"), (b, "p2")):
            n = out.merge_snapshot(
                reg.snapshot(), kinds=reg.kinds(),
                gauge_labels={"service_id": sid},
            )
            assert n == 3
        snap = out.snapshot()
        assert snap["lakesoul_w_rows_total"] == 140  # counters SUM
        # gauges keep per-process identity labels instead of clobbering
        assert snap['lakesoul_w_depth{service_id="p1"}'] == 3
        assert snap['lakesoul_w_depth{service_id="p2"}'] == 9
        h = snap["lakesoul_w_seconds"]
        assert h["count"] == 2 and h["sum"] == pytest.approx(5.5)
        assert h["buckets"][1.0] == 1 and h["buckets"][10.0] == 2  # bucket-aware

    def test_no_bucket_histogram_value_folds_at_mean(self):
        out = MetricsRegistry()
        out.merge_snapshot(
            {'lakesoul_scan_stage_seconds{stage="decode"}': {
                "sum": 0.3, "count": 3,
            }},
            kinds={"lakesoul_scan_stage_seconds": "histogram"},
            labels={"worker": "wX"},
        )
        series = out.series("lakesoul_scan_stage_seconds")
        assert len(series) == 1
        labels, h = series[0]
        assert labels == {"stage": "decode", "worker": "wX"}
        assert h.value["count"] == 3 and h.value["sum"] == pytest.approx(0.3)

    def test_kind_clash_and_garbage_series_skipped_not_fatal(self):
        out = MetricsRegistry()
        out.counter("lakesoul_w_clash_total").inc(1)
        merged = out.merge_snapshot(
            {
                "lakesoul_w_clash_total": {"sum": 1.0, "count": 1},  # kindclash
                "{not a series}": 5,
                "lakesoul_w_ok_total": 2,
            },
            kinds={},
        )
        assert merged == 1  # only the good series
        assert out.snapshot()["lakesoul_w_ok_total"] == 2
        assert out.snapshot()["lakesoul_w_clash_total"] == 1  # untouched


# --------------------------------------------------------------- exporter


class _RaisingSource:
    def prometheus_text(self):
        raise RuntimeError("collector exploded")

    def snapshot(self):
        raise RuntimeError("collector exploded")


class _DocSource:
    def prometheus_text(self):
        return "# TYPE lakesoul_t_total counter\nlakesoul_t_total 1\n"

    def snapshot(self):
        return {"lakesoul_t_total": 1}


class TestExporter:
    def _get(self, port, path, accept=None):
        req = urllib.request.Request(
            f"http://127.0.0.1:{port}{path}",
            headers={"Accept": accept} if accept else {},
        )
        with urllib.request.urlopen(req) as r:
            return r.status, r.headers.get("Content-Type"), r.read().decode()

    def test_content_negotiation_health_and_500_body(self):
        srv = serve_prometheus(_DocSource(), port=0, host="127.0.0.1")
        try:
            port = srv.server_address[1]
            status, ctype, body = self._get(port, "/metrics")
            assert status == 200 and ctype.startswith("text/plain")
            assert "lakesoul_t_total 1" in body
            status, ctype, body = self._get(
                port, "/metrics", accept="application/json"
            )
            assert status == 200 and ctype == "application/json"
            assert json.loads(body) == {"lakesoul_t_total": 1}
            fleet.process_identity(role="exporter-test")
            status, _, body = self._get(port, "/healthz")
            doc = json.loads(body)
            assert status == 200 and doc["status"] == "ok"
            assert doc["role"] == "exporter-test" and doc["pid"] == os.getpid()
        finally:
            srv.shutdown()

    def test_raising_source_returns_500_body_not_dropped_socket(self):
        srv = serve_prometheus(_RaisingSource(), port=0, host="127.0.0.1")
        try:
            port = srv.server_address[1]
            with pytest.raises(urllib.error.HTTPError) as ei:
                self._get(port, "/metrics")
            assert ei.value.code == 500
            body = ei.value.read().decode()
            assert "RuntimeError" in body and "collector exploded" in body
            # liveness stays up even when metrics production is broken
            status, _, _ = self._get(port, "/healthz")
            assert status == 200
        finally:
            srv.shutdown()


# ------------------------------------------------------- identity + publish


class TestIdentityAndPublisher:
    def test_arm_without_spool_stamps_identity_gauges_only(self, monkeypatch):
        monkeypatch.delenv(fleet.ENV_SPOOL, raising=False)
        pub = fleet.arm("unit-test-role", service_id="unit-test-1")
        assert pub is None
        snap = registry().snapshot()
        build = [
            k for k in snap
            if k.startswith("lakesoul_build_info")
            and 'role="unit-test-role"' in k
            and 'service_id="unit-test-1"' in k
        ]
        assert build and snap[build[0]] == 1
        start = [
            k for k in snap
            if k.startswith("lakesoul_process_start_time_seconds")
            and 'service_id="unit-test-1"' in k
        ]
        assert start and snap[start[0]] == pytest.approx(time.time(), abs=120)
        labels = fleet.identity_labels(worker="w")
        assert labels["role"] == "unit-test-role"
        assert labels["service_id"] == "unit-test-1"
        assert labels["worker"] == "w"

    def test_publisher_flush_writes_member_and_recorder_docs(self, spool):
        fleet.process_identity(role="pubtest", service_id="pubtest-1")
        src = MetricsRegistry()
        src.counter("lakesoul_pub_rows_total").inc(12)
        pub = fleet.FleetPublisher(spool, flush_s=60.0, source=src)
        fleet.record_event("pubtest.step", detail="x")
        pub.flush(reason="unit")
        member = json.load(open(os.path.join(spool, "member-pubtest-1.json")))
        assert member["role"] == "pubtest"
        assert member["pid"] == os.getpid()
        assert member["snapshot"]["lakesoul_pub_rows_total"] == 12
        assert member["kinds"]["lakesoul_pub_rows_total"] == "counter"
        assert member["heartbeat_unix"] == pytest.approx(time.time(), abs=60)
        rec = json.load(open(os.path.join(spool, "recorder-pubtest-1.json")))
        assert rec["reason"] == "unit"
        assert any(e["name"] == "pubtest.step" for e in rec["events"])
        # flush cost is metered (the bench budgets it)
        flush_h = src.histogram(fleet.FLUSH_FAMILY).value
        assert flush_h["count"] >= 1

    def test_periodic_flush_and_stop(self, spool):
        fleet.process_identity(role="pubtest", service_id="pubtest-2")
        src = MetricsRegistry()
        pub = fleet.FleetPublisher(spool, flush_s=0.05, source=src)
        pub.start()
        try:
            deadline = time.monotonic() + 5.0
            path = os.path.join(spool, "member-pubtest-2.json")
            first = json.load(open(path))["heartbeat_unix"]
            beat = first
            while time.monotonic() < deadline and beat <= first:
                time.sleep(0.05)
                beat = json.load(open(path))["heartbeat_unix"]
            assert beat > first, "periodic flush never advanced the heartbeat"
        finally:
            pub.stop()

    def test_child_env_pins_trace_and_spool(self, spool, monkeypatch):
        from lakesoul_tpu.obs.tracing import ENV_TRACE_ID, span

        monkeypatch.delenv(ENV_TRACE_ID, raising=False)
        monkeypatch.delenv(fleet.ENV_SPOOL, raising=False)
        monkeypatch.setattr(fleet, "_PUBLISHER", None)
        env = fleet.child_env()
        assert ENV_TRACE_ID not in env and fleet.ENV_SPOOL not in env
        with span("parent.op") as s:
            env = fleet.child_env()
            assert env[ENV_TRACE_ID] == s.trace_id
        env = fleet.child_env(trace_id="pinned-id-1")
        assert env[ENV_TRACE_ID] == "pinned-id-1"
        pub = fleet.FleetPublisher(spool, flush_s=60.0, source=MetricsRegistry())
        monkeypatch.setattr(fleet, "_PUBLISHER", pub)
        assert fleet.child_env()[fleet.ENV_SPOOL] == spool


# ------------------------------------------------------------- aggregation


class TestFleetAggregator:
    def test_one_snapshot_from_many_members_with_staleness(self, spool):
        now = time.time()
        _member(
            spool, role="scanplane-worker", service_id="w1",
            snapshot={
                "lakesoul_scanplane_client_rows_total": 600,
                'lakesoul_build_info{role="scanplane-worker",service_id="w1",version="0.1.0"}': 1,
            },
            kinds={
                "lakesoul_scanplane_client_rows_total": "counter",
                "lakesoul_build_info": "gauge",
            },
            started_unix=now - 10.0, chips=2,
        )
        _member(
            spool, role="scanplane-worker", service_id="w2",
            snapshot={"lakesoul_scanplane_client_rows_total": 400},
            kinds={"lakesoul_scanplane_client_rows_total": "counter"},
            started_unix=now - 5.0, chips=2,
        )
        _member(
            spool, role="compactor", service_id="c1",
            snapshot={"lakesoul_compaction_jobs_total": 3},
            kinds={"lakesoul_compaction_jobs_total": "counter"},
            heartbeat_unix=now - 60.0, started_unix=now - 90.0,
        )
        agg = fleet.FleetAggregator(spool, stale_after_s=5.0)
        doc = agg.aggregate(now=now)
        assert len(doc["members"]) == 3
        by_sid = {m["service_id"]: m for m in doc["members"]}
        assert not by_sid["w1"]["stale"] and not by_sid["w2"]["stale"]
        assert by_sid["c1"]["stale"]
        snap = doc["snapshot"]
        # counters SUM across the fleet into one series
        assert snap["lakesoul_scanplane_client_rows_total"] == 1000
        # per-role series survive via identity labels on gauges
        assert any(
            "lakesoul_build_info" in k and 'role="scanplane-worker"' in k
            for k in snap
        )
        # north star: rows over the fleet window (oldest member started 90s
        # ago), chips = per-host max (both workers see the same 2 devices)
        assert doc["fleet"]["rows"] == 1000
        assert doc["fleet"]["window_s"] == pytest.approx(90.0, abs=1.0)
        assert doc["fleet"]["chips"] == 2
        assert doc["fleet"]["rows_per_s_per_chip"] == pytest.approx(
            doc["fleet"]["rows_per_s"] / 2, rel=1e-3
        )
        assert snap["lakesoul_fleet_members"] == 3
        assert snap["lakesoul_fleet_stale_members"] == 1
        # prometheus view serves the same merged registry
        text = agg.prometheus_text()
        assert "lakesoul_fleet_members 3" in text
        assert "lakesoul_scanplane_client_rows_total 1000" in text

    def test_fleet_wide_freshness_slo(self, spool):
        from lakesoul_tpu.freshness.slo import (
            FRESHNESS_BUCKETS,
            FRESHNESS_FAMILY,
            VIOLATIONS_FAMILY,
        )

        src = MetricsRegistry()
        h = src.histogram(FRESHNESS_FAMILY, buckets=FRESHNESS_BUCKETS)
        for v in (0.5, 1.0, 2.0, 3.0):
            h.observe(v)
        src.counter(VIOLATIONS_FAMILY, slo="freshness_10.0s").inc(0)
        _member(
            spool, role="follower", service_id="f1",
            snapshot=json.loads(json.dumps(src.snapshot())),
            kinds=src.kinds(),
        )
        doc = fleet.FleetAggregator(spool, stale_after_s=30.0).aggregate()
        fr = doc["slos"]["freshness"]
        assert fr["count"] == 4 and fr["violations"] == 0
        assert fr["in_budget"] is True
        assert fr["mean_s"] == pytest.approx(6.5 / 4)
        assert 0.0 < fr["p50_s"] <= fr["p99_s"]
        tp = doc["slos"]["throughput"]
        assert tp["ok"] is None  # no floor requested
        doc = fleet.FleetAggregator(spool, stale_after_s=30.0).aggregate(
            min_rows_per_s=10.0**9
        )
        assert doc["slos"]["throughput"]["ok"] is False

    def test_trace_assembly_across_members(self, spool):
        tid = "trace-abc"
        _recorder(
            spool, role="freshness-writer", service_id="fw", pid=10,
            spans=[
                {"name": "freshness.commit", "trace_id": tid, "t_unix": 1.0},
                {"name": "unrelated", "trace_id": "other", "t_unix": 1.5},
            ],
        )
        _recorder(
            spool, role="scanplane-worker", service_id="sw", pid=20,
            spans=[{
                "name": "scanplane.range.produce", "trace_id": tid,
                "t_unix": 2.0,
            }],
        )
        _recorder(
            spool, role="scanplane-drive", service_id="dr", pid=30,
            spans=[{
                "name": "scanplane.drive.deliver", "trace_id": tid,
                "t_unix": 3.0,
            }],
        )
        trace = fleet.FleetAggregator(spool).trace(tid)
        assert [s["name"] for s in trace] == [
            "freshness.commit", "scanplane.range.produce",
            "scanplane.drive.deliver",
        ]
        assert [s["pid"] for s in trace] == [10, 20, 30]
        assert len({s["pid"] for s in trace}) >= 2  # spans ≥ 2 processes

    def test_postmortem_recovers_killed_members_last_moments(self, spool):
        """The in-process SIGKILL leg: a member whose heartbeat stopped is
        stale, and its flight-recorder dump + last-flushed snapshot are
        recoverable from the spool."""
        fleet.process_identity(role="victim-role", service_id="victim-1")
        src = MetricsRegistry()
        src.counter("lakesoul_victim_rows_total").inc(77)
        pub = fleet.FleetPublisher(spool, flush_s=60.0, source=src)
        fleet.record_event(
            "scanplane.range.lease", session="s1", range=4, fence=1
        )
        pub.flush(reason="scanplane.range.lease")
        # no further flushes — the process is "SIGKILLed" here
        time.sleep(0.06)
        agg = fleet.FleetAggregator(spool, stale_after_s=0.05)
        stale = agg.stale_members()
        assert [m["service_id"] for m in stale] == ["victim-1"]
        pms = agg.postmortems()
        assert len(pms) == 1
        pm = pms[0]
        assert pm["role"] == "victim-role"
        last = [e for e in pm["events"] if e["name"] == "scanplane.range.lease"]
        assert last and last[-1]["attrs"]["range"] == 4
        assert pm["last_snapshot"]["lakesoul_victim_rows_total"] == 77

    def test_torn_or_garbage_files_are_skipped(self, spool):
        with open(os.path.join(spool, "member-torn.json"), "w") as f:
            f.write('{"role": "x", ')
        with open(os.path.join(spool, "member-list.json"), "w") as f:
            f.write("[1, 2]")
        _member(spool, role="ok", service_id="ok1", snapshot={})
        doc = fleet.FleetAggregator(spool, stale_after_s=30.0).aggregate()
        assert [m["service_id"] for m in doc["members"]] == ["ok1"]


# ------------------------------------------------- client stage-merge dedup


class TestClientStageMergeCompat:
    def _client(self):
        from lakesoul_tpu.scanplane.client import ScanPlaneClient

        return ScanPlaneClient("grpc://127.0.0.1:1")

    def test_series_byte_compatible_with_stage_merge(self):
        from lakesoul_tpu.obs.stages import STAGE_FAMILY, stage_merge

        c = self._client()
        c._merge_stages(
            {"range": 0, "worker": "compatA",
             "stages": {"decode": {"s": 0.25, "count": 5},
                        "merge": {"s": 0.1, "count": 5}}},
            set(),
        )
        # the OLD hand-rolled path, distinct worker label, same deltas
        stage_merge("decode", 0.25, 5, worker="compatB")
        stage_merge("merge", 0.1, 5, worker="compatB")
        snap = registry().snapshot()
        for stage in ("decode", "merge"):
            new_key = f'{STAGE_FAMILY}{{stage="{stage}",worker="compatA"}}'
            old_key = f'{STAGE_FAMILY}{{stage="{stage}",worker="compatB"}}'
            assert new_key in snap, sorted(
                k for k in snap if k.startswith(STAGE_FAMILY)
            )
            assert snap[new_key] == snap[old_key]  # identical series values

    def test_worker_label_folding_and_dedup_by_range(self):
        c = self._client()
        c._worker_labels = {f"w{i}" for i in range(c.MAX_WORKER_LABELS)}
        merged: set = set()
        c._merge_stages(
            {"range": 1, "worker": "overflow-worker",
             "stages": {"decode": {"s": 0.5, "count": 1}}},
            merged,
        )
        snap = registry().snapshot()
        assert any('worker="other"' in k for k in snap)
        assert not any("overflow-worker" in k for k in snap)
        # a redelivered range's sidecar must not double-count
        before = dict(snap)
        c._merge_stages(
            {"range": 1, "worker": "overflow-worker",
             "stages": {"decode": {"s": 0.5, "count": 1}}},
            merged,
        )
        after = {
            k: v for k, v in registry().snapshot().items() if k in before
        }
        assert after == before


# ---------------------------------------------------------------- console


class TestConsoleFleetStatus:
    def test_fleet_status_renders_members_north_star_and_postmortems(
        self, tmp_warehouse, spool
    ):
        from lakesoul_tpu import LakeSoulCatalog
        from lakesoul_tpu.service.console import Console

        now = time.time()
        _member(
            spool, role="scanplane-worker", service_id="w1",
            snapshot={"lakesoul_scanplane_client_rows_total": 500},
            kinds={"lakesoul_scanplane_client_rows_total": "counter"},
            started_unix=now - 10.0,
        )
        _member(
            spool, role="compactor", service_id="dead1",
            snapshot={}, heartbeat_unix=now - 120.0, started_unix=now - 200.0,
        )
        _recorder(
            spool, role="compactor", service_id="dead1",
            events=[{"t_unix": now - 130.0, "name": "compaction.lease"}],
        )
        c = Console(LakeSoulCatalog(str(tmp_warehouse)))
        out = c.execute(f"fleet-status {spool}")
        assert "2 members" in out
        assert "scanplane-worker" in out and "[live]" in out
        assert "[STALE]" in out
        assert "north star" in out and "rows/s" in out
        assert "freshness SLO" in out
        assert "postmortem: compactor dead1" in out
        assert "compaction.lease" in out
        assert "fleet-status" in c.execute("help")

    def test_fleet_status_without_spool_or_members(self, tmp_warehouse, spool, monkeypatch):
        from lakesoul_tpu import LakeSoulCatalog
        from lakesoul_tpu.service.console import Console

        monkeypatch.delenv("LAKESOUL_OBS_SPOOL", raising=False)
        c = Console(LakeSoulCatalog(str(tmp_warehouse)))
        assert "no spool" in c.execute("fleet-status")
        assert "no members" in c.execute(f"fleet-status {spool}")


# --------------------------------------------------- slow: real SIGKILL leg


@pytest.mark.slow
class TestSigkillPostmortemSubprocess:
    def test_killed_worker_leaves_recoverable_postmortem(self, tmp_path):
        """SIGKILL a REAL scanplane worker mid-range (holding its lease):
        its flight-recorder dump and last-flushed snapshot are recoverable
        from the obs spool, and heartbeat age marks it stale."""
        import pathlib
        import signal
        import subprocess
        import sys

        import numpy as np
        import pyarrow as pa

        from lakesoul_tpu import LakeSoulCatalog
        from lakesoul_tpu.scanplane.session import ScanSession

        repo = str(pathlib.Path(__file__).resolve().parent.parent)
        wh, db = str(tmp_path / "wh"), str(tmp_path / "meta.db")
        catalog = LakeSoulCatalog(wh, db_path=db)
        schema = pa.schema([("id", pa.int64()), ("v", pa.float64())])
        t = catalog.create_table("t", schema, primary_keys=["id"],
                                 hash_bucket_num=2)
        rng = np.random.default_rng(5)
        ids = np.sort(rng.choice(40_000, 8_000, replace=False)).astype(np.int64)
        t.upsert(pa.table(
            {"id": ids, "v": rng.normal(size=len(ids))}, schema=schema
        ))

        spool = str(tmp_path / "spool")
        obs_spool = str(tmp_path / "obs")
        os.makedirs(spool)
        session = ScanSession.plan(catalog, {"table": "t", "batch_size": 4096})
        session.publish(spool)

        env = dict(os.environ)
        env.update({
            "JAX_PLATFORMS": "cpu",
            "PYTHONPATH": repo,
            "LAKESOUL_FAULTS": "scanplane.range:1:hang:300",
            "LAKESOUL_OBS_SPOOL": obs_spool,
            "LAKESOUL_OBS_FLUSH_S": "0.2",
        })
        victim = subprocess.Popen(
            [
                sys.executable, "-m", "lakesoul_tpu.scanplane", "worker",
                "--warehouse", wh, "--db-path", db, "--spool", spool,
                "--lease-ttl-s", "2.0", "--poll-s", "0.05",
                "--worker-id", "victim",
            ],
            env=env, stdout=subprocess.PIPE, stderr=subprocess.PIPE,
            text=True, cwd=repo,
        )
        try:
            store = catalog.client.store
            keys = [
                f"scanplane/{session.session_id}/{i}"
                for i in range(len(session.ranges))
            ]
            held = None
            deadline = time.monotonic() + 60.0
            while time.monotonic() < deadline and held is None:
                for k in keys:
                    lease = store.get_lease(k)
                    if lease is not None and lease.holder == "victim":
                        held = k
                        break
                if victim.poll() is not None:
                    _, err = victim.communicate(timeout=10.0)
                    pytest.fail(f"victim exited early: {err[-2000:]}")
                time.sleep(0.05)
            assert held is not None, "victim never leased a range"
            held_index = int(held.rsplit("/", 1)[-1])
            # the record_event(flush=True) at lease-acquire must already
            # have pinned the recorder before the hang window
            victim.send_signal(signal.SIGKILL)
            victim.wait(10.0)

            time.sleep(0.5)  # let heartbeat age past stale_after below
            agg = fleet.FleetAggregator(obs_spool, stale_after_s=0.4)
            stale = agg.stale_members()
            assert any(
                m["service_id"] == "victim" for m in stale
            ), [m.get("service_id") for m in agg.members()]
            pms = agg.postmortems()
            pm = next(p for p in pms if p["service_id"] == "victim")
            assert pm["role"] == "scanplane-worker"
            lease_events = [
                e for e in pm["events"]
                if e["name"] == "scanplane.range.lease"
            ]
            assert lease_events, pm["events"]
            assert lease_events[-1]["attrs"]["range"] == held_index
            assert lease_events[-1]["attrs"]["session"] == session.session_id
            # the last-flushed snapshot rides along: the worker had stamped
            # its build info before dying
            assert any(
                k.startswith("lakesoul_build_info")
                for k in pm["last_snapshot"]
            )
        finally:
            if victim.poll() is None:
                victim.kill()
