"""Owned disk page cache (VERDICT r1 #5): bounded LRU read-through with
hit/miss/eviction stats — the role of the reference's
rust/lakesoul-io/src/cache/disk_cache.rs + cache/read_through.rs."""

import fsspec
import numpy as np
import pyarrow as pa
import pytest

from lakesoul_tpu import LakeSoulCatalog
from lakesoul_tpu.io.object_store import cache_stats
from lakesoul_tpu.io.page_cache import DiskPageCache, get_cache


class _CountingFS:
    """Wraps an fsspec filesystem, counting ranged GETs."""

    def __init__(self, inner):
        self.inner = inner
        self.calls = []

    def cat_file(self, path, start=None, end=None):
        self.calls.append((path, start, end))
        return self.inner.cat_file(path, start=start, end=end)

    def __getattr__(self, name):
        return getattr(self.inner, name)


@pytest.fixture()
def mem_fs():
    fs = fsspec.filesystem("memory")
    yield fs
    try:
        fs.rm("/pc", recursive=True)
    except FileNotFoundError:
        pass


class TestDiskPageCache:
    def test_read_through_and_hits(self, tmp_path, mem_fs):
        data = bytes(range(256)) * 1024  # 256 KiB
        mem_fs.pipe_file("/pc/blob", data)
        target = _CountingFS(mem_fs)
        cache = DiskPageCache(str(tmp_path / "c"), page_bytes=16 << 10)

        out = cache.read_range(target, "/pc/blob", 1000, 50_000)
        assert out == data[1000:50_000]
        assert len(target.calls) == 1  # consecutive missing pages → ONE GET

        out2 = cache.read_range(target, "/pc/blob", 0, len(data))
        assert out2 == data
        s = cache.snapshot()
        assert s["hits"] >= 3  # pages 0-3 hit on the second read
        assert len(target.calls) == 2  # only the not-yet-cached tail fetched

        out3 = cache.read_range(target, "/pc/blob", 5, 100_000)
        assert out3 == data[5:100_000]
        assert len(target.calls) == 2  # fully cached: zero new GETs

    def test_eviction_bounds_bytes(self, tmp_path, mem_fs):
        data = b"z" * (64 << 10)
        cache = DiskPageCache(
            str(tmp_path / "c"), page_bytes=8 << 10, max_bytes=32 << 10
        )
        for i in range(4):
            mem_fs.pipe_file(f"/pc/f{i}", data)
            cache.read_range(mem_fs, f"/pc/f{i}", 0, len(data))
        assert cache.current_bytes() <= 32 << 10
        assert cache.snapshot()["evictions"] > 0

    def test_page_size_pinned_by_marker(self, tmp_path, mem_fs):
        # reopening a cache dir with a different page size must adopt the
        # on-disk size — indices computed at another size would map to wrong
        # byte ranges (silent corruption)
        data = bytes(range(256)) * 64  # 16 KiB
        mem_fs.pipe_file("/pc/marker", data)
        d = str(tmp_path / "c")
        c1 = DiskPageCache(d, page_bytes=4 << 10)
        c1.read_range(mem_fs, "/pc/marker", 0, len(data))
        c2 = DiskPageCache(d, page_bytes=1 << 10)  # conflicting knob
        assert c2.page_bytes == 4 << 10
        assert c2.read_range(mem_fs, "/pc/marker", 3000, 9000) == data[3000:9000]

    def test_index_survives_restart(self, tmp_path, mem_fs):
        data = b"q" * (32 << 10)
        mem_fs.pipe_file("/pc/persist", data)
        d = str(tmp_path / "c")
        cache = DiskPageCache(d, page_bytes=8 << 10)
        cache.read_range(mem_fs, "/pc/persist", 0, len(data))

        target = _CountingFS(mem_fs)
        cache2 = DiskPageCache(d, page_bytes=8 << 10)  # fresh index from disk
        out = cache2.read_range(target, "/pc/persist", 0, len(data))
        assert out == data
        assert target.calls == []  # served entirely from the restarted cache


class TestCachedTableScan:
    def _remote_table(self, mem_fs, cache_dir):
        opts = {"lakesoul.cache_dir": str(cache_dir)}
        catalog = LakeSoulCatalog(
            "memory://wh",
            storage_options=opts,
            db_path=str(cache_dir.parent / "meta.db"),
        )
        schema = pa.schema([("id", pa.int64()), ("v", pa.float64())])
        t = catalog.create_table("remote", schema, primary_keys=["id"], hash_bucket_num=2)
        rng = np.random.default_rng(0)
        n = 50_000
        t.write_arrow(
            pa.table({"id": np.arange(n, dtype=np.int64), "v": rng.normal(size=n)})
        )
        t.upsert(
            pa.table(
                {
                    "id": rng.choice(n, n // 10, replace=False).astype(np.int64),
                    "v": rng.normal(size=n // 10),
                }
            )
        )
        return t, opts

    def test_second_scan_hits_cache(self, tmp_path, mem_fs):
        t, opts = self._remote_table(mem_fs, tmp_path / "cache")
        first = t.to_arrow()
        stats1 = cache_stats(opts)
        assert stats1["misses"] > 0  # cold: fetched from the store
        second = t.to_arrow()
        stats2 = cache_stats(opts)
        assert second.sort_by("id").equals(first.sort_by("id"))
        new_hits = stats2["hits"] - stats1["hits"]
        new_misses = stats2["misses"] - stats1["misses"]
        # VERDICT 'done' criterion: >90% of the second scan served from cache
        assert new_hits / max(1, new_hits + new_misses) > 0.9, (stats1, stats2)

    def test_writes_bypass_cache(self, tmp_path, mem_fs):
        t, opts = self._remote_table(mem_fs, tmp_path / "cache")
        before = cache_stats(opts)
        t.write_arrow(
            pa.table({"id": pa.array([999_999], type=pa.int64()), "v": [1.0]})
        )
        after = cache_stats(opts)
        assert after["misses"] == before["misses"]  # no read-through on write


class TestCacheConcurrency:
    def test_threads_share_one_cache_safely(self, tmp_path, mem_fs):
        """Concurrent readers over one DiskPageCache: every read returns
        correct bytes, accounting stays consistent, no deadlock."""
        import threading

        data = bytes(range(256)) * 512  # 128 KiB
        mem_fs.pipe_file("/pc/conc", data)
        cache = DiskPageCache(str(tmp_path / "c"), page_bytes=8 << 10)
        errors = []

        def reader(seed):
            rng = __import__("numpy").random.default_rng(seed)
            try:
                for _ in range(40):
                    a = int(rng.integers(0, len(data) - 1))
                    b = int(rng.integers(a + 1, len(data) + 1))
                    got = cache.read_range(mem_fs, "/pc/conc", a, b)
                    if got != data[a:b]:
                        errors.append((a, b))
            except Exception as e:  # pragma: no cover
                errors.append(e)

        threads = [threading.Thread(target=reader, args=(i,)) for i in range(6)]
        for t in threads:
            t.start()
        for t in threads:
            # generous: on a loaded 1-core host an expired join would leave
            # readers racing the accounting snapshot below (flaky mismatch);
            # a genuine deadlock fails the explicit liveness assert instead
            t.join(timeout=120)
        assert not any(t.is_alive() for t in threads), "reader deadlocked"
        assert not errors
        s = cache.snapshot()
        assert s["bytes"] == sum(
            v for v in cache._index.values()
        )
        assert s["hits"] > 0

    def test_eviction_under_concurrency_keeps_bound(self, tmp_path, mem_fs):
        import threading

        blobs = {}
        for i in range(4):
            blobs[i] = bytes([i]) * (64 << 10)
            mem_fs.pipe_file(f"/pc/c{i}", blobs[i])
        cache = DiskPageCache(str(tmp_path / "c"), page_bytes=8 << 10, max_bytes=48 << 10)
        errors = []

        def reader(i):
            try:
                for _ in range(20):
                    got = cache.read_range(mem_fs, f"/pc/c{i}", 0, 64 << 10)
                    if got != blobs[i]:
                        errors.append(i)
            except Exception as e:  # pragma: no cover
                errors.append(e)

        threads = [threading.Thread(target=reader, args=(i,)) for i in range(4)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=120)
        assert not any(t.is_alive() for t in threads), "reader deadlocked"
        assert not errors
        assert cache.current_bytes() <= 48 << 10


class TestReadahead:
    def test_readahead_prefetches_next_pages_on_runtime_pool(self, tmp_path):
        import time

        import fsspec

        mem_fs = fsspec.filesystem("memory")
        blob = bytes(range(256)) * 2048  # 512 KiB
        mem_fs.pipe_file("/ra/seq", blob)
        cache = DiskPageCache(
            str(tmp_path / "ra"), page_bytes=32 << 10, readahead_pages=2
        )
        got = cache.read_range(mem_fs, "/ra/seq", 0, 1000)
        assert got == blob[:1000]
        deadline = time.time() + 5
        while time.time() < deadline and cache.snapshot()["readahead_pages"] < 2:
            time.sleep(0.02)
        snap = cache.snapshot()
        assert snap["readahead_pages"] == 2
        # the prefetched pages now serve as pure hits (no new miss)
        got = cache.read_range(mem_fs, "/ra/seq", 32 << 10, (64 << 10) + 10)
        assert got == blob[32 << 10 : (64 << 10) + 10]
        snap2 = cache.snapshot()
        assert snap2["misses"] == snap["misses"]
        assert snap2["hits"] > snap["hits"]

    def test_readahead_stops_at_eof_instead_of_refetching(self, tmp_path):
        import time

        class CountingMem:
            def __init__(self, inner):
                self.inner = inner
                self.gets = 0

            def cat_file(self, *a, **k):
                self.gets += 1
                return self.inner.cat_file(*a, **k)

        import fsspec

        mem = fsspec.filesystem("memory")
        mem.pipe_file("/ra/small", b"x" * (40 << 10))  # 1.25 pages of 32K
        counting = CountingMem(mem)
        cache = DiskPageCache(
            str(tmp_path / "eof"), page_bytes=32 << 10, readahead_pages=2
        )
        cache.read_range(counting, "/ra/small", 0, 100)
        time.sleep(0.4)
        after_first = counting.gets
        # repeated tail reads must NOT keep re-issuing past-EOF readahead
        for _ in range(5):
            cache.read_range(counting, "/ra/small", 0, 100)
        time.sleep(0.4)
        assert counting.gets == after_first, (counting.gets, after_first)

    def test_readahead_with_cached_gap_never_corrupts(self, tmp_path):
        """A page already cached in the readahead window must not shift the
        coalesced GET's positional slicing: every page served afterwards
        must hold its own bytes (regression: gapped `want` list stored page
        k+1's bytes under index k+2)."""
        import time

        import fsspec

        mem = fsspec.filesystem("memory")
        pb = 16 << 10
        blob = b"".join(bytes([i]) * pb for i in range(8))  # page i = byte i
        mem.pipe_file("/ra/gap", blob)
        cache = DiskPageCache(
            str(tmp_path / "gap"), page_bytes=pb, readahead_pages=4
        )
        # seed page 2 in the cache first (scattered read)
        cache.read_range(mem, "/ra/gap", 2 * pb, 2 * pb + 10)
        time.sleep(0.3)
        # read page 0: readahead window [1..4] contains the cached page 2
        cache.read_range(mem, "/ra/gap", 0, 10)
        time.sleep(0.5)
        for page in range(8):
            got = cache.read_range(mem, "/ra/gap", page * pb, page * pb + 100)
            assert got == bytes([page]) * 100, f"page {page} corrupted"

    def test_readahead_off_by_default_and_env(self, tmp_path, monkeypatch):
        import fsspec

        from lakesoul_tpu.io import page_cache as pc_mod

        assert DiskPageCache(str(tmp_path / "d0")).readahead_pages == 0
        monkeypatch.setenv("LAKESOUL_CACHE_READAHEAD_PAGES", "3")
        assert DiskPageCache(str(tmp_path / "d1")).readahead_pages == 3
        # storage-option plumbing retunes an existing cache
        c = pc_mod.get_cache(str(tmp_path / "d1"), readahead_pages="1")
        assert c.readahead_pages == 1
