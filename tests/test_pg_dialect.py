"""Static PG-dialect validation of every SQL statement the store emits.

The image carries no PostgreSQL server, no psycopg2, and no sqlglot/pglast
(pip installs are off), so full parse-validation against a live PG is
impossible HERE — the real-PG leg exists as the skipped ``pg-real`` backend
in tests/test_metadata.py and runs wherever ``LAKESOUL_TEST_PG_DSN`` points
at a server.  This suite is the in-image substitute (VERDICT r3 item 7):
it drives a COMPREHENSIVE metadata workload through the PostgresMetadataStore
(psycopg2 fake), captures every statement AFTER dialect translation, and
statically rejects anything PG would not accept — SQLite-isms, untranslated
placeholders, placeholder/parameter arity drift.
"""

import re
import sys

import pyarrow as pa
import pytest

import fake_psycopg2

from lakesoul_tpu.meta import CommitOp, DataFileOp, MetaDataClient, PartitionInfo
from lakesoul_tpu.meta.entity import DataCommitInfo

SCHEMA = pa.schema([("id", pa.int64()), ("v", pa.float64()), ("p", pa.string())])


@pytest.fixture()
def captured(tmp_path, monkeypatch):
    """(client, list of (sql, params) as sent to the PG driver)."""
    monkeypatch.setitem(sys.modules, "psycopg2", fake_psycopg2)
    from lakesoul_tpu.meta.store import PostgresMetadataStore

    dsn = f"postgresql://fake/{tmp_path.name}-dialect"
    from lakesoul_tpu.meta.store import translate_sql

    store = PostgresMetadataStore(dsn)
    statements: list[tuple[str, tuple]] = []
    real_exec = store._exec

    def spy(conn, sql, params=()):
        # record what the PG DRIVER receives (post-translation, exactly the
        # transform _exec applies before cursor.execute)
        statements.append((translate_sql(sql, store.PARAMSTYLE), tuple(params)))
        return real_exec(conn, sql, params)

    store._exec = spy
    yield MetaDataClient(store=store), statements
    fake_psycopg2.reset(dsn)


def _exercise(client: MetaDataClient) -> None:
    """Touch every DAO code path: DDL, all five commit ops, scan planning,
    prefix ranges, time travel, canonicalization, cleaner, config."""
    client.create_namespace("ns1")
    info = client.create_table(
        "t", "/wh/t", SCHEMA, primary_keys=["id"], range_partitions=["p"]
    )
    for i, p in enumerate(["a", "b"]):
        client.commit_data_files(
            info,
            {f"p={p}": [DataFileOp(path=f"/wh/t/p={p}/f{i}_0000.parquet", size=10)]},
            CommitOp.APPEND,
        )
    client.commit_data_files(
        info, {"p=a": [DataFileOp(path="/wh/t/p=a/g_0000.parquet", size=9)]},
        CommitOp.MERGE,
    )
    head = client.store.get_latest_partition_info(info.table_id, "p=a")
    client.commit_data_files(
        info, {"p=a": [DataFileOp(path="/wh/t/p=a/c_0000.parquet")]},
        CommitOp.COMPACTION, read_partition_info=[head],
    )
    client.commit_data_files(info, {"p=b": []}, CommitOp.DELETE)
    # planner paths: full scan, point lookup, prefix range, legacy fallback
    client.get_scan_plan_partitions("t")
    client.get_scan_plan_partitions("t", {"p": "a"})
    client.store.insert_data_commit_info(
        [DataCommitInfo(table_id=info.table_id, partition_desc="x=1,p=z",
                        commit_id=DataCommitInfo.new_commit_id(),
                        file_ops=[DataFileOp(path="/wh/t/legacy_0000.parquet")],
                        committed=True, timestamp=1)]
    )
    client.store.transaction_insert_partition_info(
        [PartitionInfo(table_id=info.table_id, partition_desc="x=1,p=z",
                       version=0, timestamp=1, snapshot=[])]
    )
    client.get_scan_plan_partitions("t", {"p": "a"})
    client.canonicalize_partition_descs("t")
    # time travel, version chains, cleaner, config
    client.store.get_partition_at_timestamp(info.table_id, "p=a", 10**15)
    client.store.get_partition_versions(info.table_id, "p=a", 0, 5)
    client.store.delete_partition_versions_before(info.table_id, "p=a", 1)
    client.store.set_global_config("k", "v")
    client.store.update_global_config("k", lambda old: (old or "") + "x")
    client.store.get_global_config("k")
    client.list_namespaces()
    client.drop_table("t")


_VERBS = ("SELECT", "INSERT", "UPDATE", "DELETE", "CREATE", "BEGIN", "COMMIT",
          "ROLLBACK", "DROP")

# things PG rejects (or that mean a translation was missed)
_FORBIDDEN = (
    re.compile(r"INSERT\s+OR\s+IGNORE", re.I),
    re.compile(r"\bAUTOINCREMENT\b", re.I),
    re.compile(r"\bPRAGMA\b", re.I),
    re.compile(r"\browid\b", re.I),
    re.compile(r"\bsqlite_", re.I),
    re.compile(r"`"),            # backtick identifiers
    re.compile(r"\bGLOB\b", re.I),
    re.compile(r"\bIFNULL\s*\(", re.I),   # PG spells it COALESCE
    re.compile(r"\bdatetime\s*\(", re.I),  # sqlite date functions
)


class TestEmittedDialect:
    def test_workload_emits_only_pg_safe_statements(self, captured):
        client, statements = captured
        _exercise(client)
        assert len(statements) > 40, "exercise did not cover the DAO surface"
        for sql, params in statements:
            head = sql.lstrip().split(None, 1)[0].upper()
            assert head in _VERBS, f"unexpected statement verb: {sql[:60]}"
            assert "?" not in sql, f"untranslated qmark placeholder: {sql[:80]}"
            for rx in _FORBIDDEN:
                assert not rx.search(sql), f"SQLite-ism {rx.pattern!r} in: {sql[:80]}"
            # placeholder/parameter arity must agree exactly
            n_ph = len(re.findall(r"%s", sql))
            assert n_ph == len(params), (
                f"{n_ph} placeholders vs {len(params)} params in: {sql[:80]}"
            )
            assert sql.count("(") == sql.count(")"), f"unbalanced parens: {sql[:80]}"

    def test_schema_ddl_is_pg_dialect(self, monkeypatch):
        # the schema DDL runs at store construction (before any spy can
        # attach) — validate the exact _PG_SCHEMA text the store executes
        monkeypatch.setitem(sys.modules, "psycopg2", fake_psycopg2)
        from lakesoul_tpu.meta.store import PostgresMetadataStore

        ddl = PostgresMetadataStore._PG_SCHEMA
        assert "CREATE TABLE" in ddl
        assert "BLOB" not in ddl.upper(), "PG has no BLOB type (use BYTEA)"
        assert "BYTEA" in ddl
        assert re.search(r"timestamp\s+BIGINT", ddl), "sqlite INTEGER ts must widen"
        for rx in _FORBIDDEN:
            assert not rx.search(ddl)

    def test_conflict_clauses_are_pg_spelling(self, captured):
        client, statements = captured
        _exercise(client)
        conflicty = [s for s, _ in statements if "CONFLICT" in s.upper()]
        for s in conflicty:
            assert re.search(r"ON\s+CONFLICT", s, re.I)
