"""Storage proxy tests + smoke-runs of the examples."""

import subprocess
import sys
import urllib.error
import urllib.request

import pyarrow as pa
import pytest

from lakesoul_tpu import LakeSoulCatalog
from lakesoul_tpu.service.jwt import Claims
from lakesoul_tpu.service.storage_proxy import StorageProxy


SCHEMA = pa.schema([("id", pa.int64()), ("v", pa.float64())])


@pytest.fixture()
def proxy_env(tmp_warehouse):
    catalog = LakeSoulCatalog(str(tmp_warehouse))
    t = catalog.create_table("t", SCHEMA)
    t.write_arrow(pa.table({"id": [1], "v": [1.0]}))
    proxy = StorageProxy(catalog, jwt_secret="pxy")
    proxy.start()
    token = proxy.jwt_server.create_token(Claims(sub="u", group="public"))
    yield catalog, proxy, token, t
    proxy.stop()


def _request(url, method="GET", token=None, data=None):
    req = urllib.request.Request(url, method=method, data=data)
    if token:
        req.add_header("Authorization", f"Bearer {token}")
    return urllib.request.urlopen(req, timeout=5)


class TestStorageProxy:
    def test_get_data_file_through_proxy(self, proxy_env):
        catalog, proxy, token, t = proxy_env
        file_path = t.scan().scan_plan()[0].data_files[0]
        rel = file_path.replace(catalog.warehouse + "/", "")
        resp = _request(f"http://127.0.0.1:{proxy.port}/{rel}", token=token)
        data = resp.read()
        assert data[:4] == b"PAR1"  # a real parquet file came back

    def test_put_round_trip(self, proxy_env):
        catalog, proxy, token, t = proxy_env
        url = f"http://127.0.0.1:{proxy.port}/default/t/extra.bin"
        resp = _request(url, method="PUT", token=token, data=b"hello")
        assert resp.status == 201
        got = _request(url, token=token).read()
        assert got == b"hello"

    def test_auth_and_rbac_enforced(self, proxy_env):
        catalog, proxy, token, t = proxy_env
        url = f"http://127.0.0.1:{proxy.port}/default/t/x"
        with pytest.raises(urllib.error.HTTPError) as e:
            _request(url)  # no token
        assert e.value.code == 401
        # private table in another domain
        catalog.client.create_table(
            "priv", f"{catalog.warehouse}/default/priv", SCHEMA, domain="teamZ"
        )
        with pytest.raises(urllib.error.HTTPError) as e:
            _request(f"http://127.0.0.1:{proxy.port}/default/priv/x", token=token)
        assert e.value.code == 403
        with pytest.raises(urllib.error.HTTPError) as e:
            _request(f"http://127.0.0.1:{proxy.port}/default/t/missing", token=token)
        assert e.value.code == 404


class TestExamples:
    def test_titanic_example(self, tmp_path):
        out = subprocess.run(
            [sys.executable, "examples/titanic_mlp.py", "--warehouse", str(tmp_path / "wh"),
             "--epochs", "3"],
            capture_output=True, text=True, timeout=300,
            env={**__import__("os").environ, "JAX_PLATFORMS": "cpu"},
        )
        assert out.returncode == 0, out.stderr[-2000:]
        assert "train accuracy" in out.stdout

    def test_bert_example(self):
        import os

        env = {**os.environ, "JAX_PLATFORMS": "cpu",
               "XLA_FLAGS": "--xla_force_host_platform_device_count=8"}
        out = subprocess.run(
            [sys.executable, "examples/bert_mlm_from_table.py"],
            capture_output=True, text=True, timeout=600, env=env,
        )
        assert out.returncode == 0, out.stderr[-2000:]
        assert "steps, loss" in out.stdout

    def test_resnet_example(self):
        import os

        env = {**os.environ, "JAX_PLATFORMS": "cpu",
               "XLA_FLAGS": "--xla_force_host_platform_device_count=8"}
        out = subprocess.run(
            [sys.executable, "examples/resnet_from_table.py"],
            capture_output=True, text=True, timeout=600, env=env,
        )
        assert out.returncode == 0, out.stderr[-2000:]
        assert "steps, loss" in out.stdout

    def test_flight_sql_gateway_example(self):
        import os

        env = {**os.environ, "JAX_PLATFORMS": "cpu"}
        out = subprocess.run(
            [sys.executable, "examples/flight_sql_gateway.py"],
            capture_output=True, text=True, timeout=600, env=env,
        )
        assert out.returncode == 0, out.stderr[-2000:]
        assert out.stdout.strip().endswith("OK")


class TestProxyRangeRequests:
    """VERDICT r1 weak #7: streamed bodies + HTTP Range support so parquet
    readers can pull footers/column chunks through the proxy."""

    def _put_blob(self, proxy, token, data):
        url = f"http://127.0.0.1:{proxy.port}/default/t/blob.bin"
        _request(url, method="PUT", token=token, data=data)
        return url

    def test_range_modes(self, proxy_env):
        catalog, proxy, token, t = proxy_env
        data = bytes(range(256)) * 40  # 10240 bytes
        url = self._put_blob(proxy, token, data)

        def get_range(hdr):
            req = urllib.request.Request(url)
            req.add_header("Authorization", f"Bearer {token}")
            req.add_header("Range", hdr)
            return urllib.request.urlopen(req, timeout=5)

        r = get_range("bytes=100-199")
        assert r.status == 206
        assert r.headers["Content-Range"] == f"bytes 100-199/{len(data)}"
        assert r.read() == data[100:200]

        r = get_range("bytes=10000-")  # open-ended
        assert r.read() == data[10000:]

        r = get_range("bytes=-16")  # suffix (parquet footer read pattern)
        assert r.read() == data[-16:]

    def test_unsatisfiable_range_416(self, proxy_env):
        catalog, proxy, token, t = proxy_env
        url = self._put_blob(proxy, token, b"tiny")
        req = urllib.request.Request(url)
        req.add_header("Authorization", f"Bearer {token}")
        req.add_header("Range", "bytes=100-200")
        with pytest.raises(urllib.error.HTTPError) as e:
            urllib.request.urlopen(req, timeout=5)
        assert e.value.code == 416
        assert e.value.headers["Content-Range"] == "bytes */4"

    def test_head_advertises_ranges(self, proxy_env):
        catalog, proxy, token, t = proxy_env
        url = self._put_blob(proxy, token, b"abcdef")
        resp = _request(url, method="HEAD", token=token)
        assert resp.headers["Accept-Ranges"] == "bytes"
        assert resp.headers["Content-Length"] == "6"

    def test_large_body_streams_round_trip(self, proxy_env):
        catalog, proxy, token, t = proxy_env
        data = b"x" * (3 << 20) + b"END"  # spans multiple CHUNKs both ways
        url = self._put_blob(proxy, token, data)
        got = _request(url, token=token).read()
        assert got == data


class TestParseRange:
    def test_parse_cases(self):
        from lakesoul_tpu.service.storage_proxy import parse_range

        assert parse_range(None, 100) is None
        assert parse_range("bytes=0-49", 100) == (0, 50)
        assert parse_range("bytes=50-", 100) == (50, 100)
        assert parse_range("bytes=-10", 100) == (90, 100)
        assert parse_range("bytes=90-150", 100) == (90, 100)  # clamped tail
        for bad in ("bytes=100-", "bytes=5-2", "bytes=-0", "items=0-1", "bytes=0-1,5-6"):
            with pytest.raises(ValueError):
                parse_range(bad, 100)


class TestOnlineFeaturesExample:
    def test_online_features_example(self, tmp_path):
        out = subprocess.run(
            [sys.executable, "examples/online_features.py", "--warehouse",
             str(tmp_path / "wh")],
            capture_output=True, text=True, timeout=300,
            env={**__import__("os").environ, "JAX_PLATFORMS": "cpu"},
        )
        assert out.returncode == 0, out.stderr[-2000:]
        assert "online features updated" in out.stdout


class TestProxyBasicAuth:
    def test_basic_credentials_accepted(self, tmp_warehouse):
        from lakesoul_tpu import LakeSoulCatalog
        from lakesoul_tpu.service.jwt import UserRegistry
        from lakesoul_tpu.service.storage_proxy import StorageProxy
        import base64

        catalog = LakeSoulCatalog(str(tmp_warehouse))
        t = catalog.create_table("pb", SCHEMA)
        t.write_arrow(pa.table({"id": [1], "v": [1.0]}))
        UserRegistry(catalog.client).register("carol", "pw9")
        proxy = StorageProxy(catalog, jwt_secret="pxy")
        proxy.start()
        try:
            file_path = t.scan().scan_plan()[0].data_files[0]
            rel = file_path.replace(catalog.warehouse + "/", "")
            cred = base64.b64encode(b"carol:pw9").decode()
            req = urllib.request.Request(f"http://127.0.0.1:{proxy.port}/{rel}")
            req.add_header("Authorization", f"Basic {cred}")
            data = urllib.request.urlopen(req, timeout=10).read()
            assert data[:4] == b"PAR1"
            # wrong password rejected
            bad = base64.b64encode(b"carol:nope").decode()
            req2 = urllib.request.Request(f"http://127.0.0.1:{proxy.port}/{rel}")
            req2.add_header("Authorization", f"Basic {bad}")
            with pytest.raises(urllib.error.HTTPError) as e:
                urllib.request.urlopen(req2, timeout=10)
            assert e.value.code == 401
        finally:
            proxy.stop()
