"""Storage-proxy upstream mode: SigV4 re-signing + DNS discovery/failover.

Role parity with rust/lakesoul-s3-proxy/src/aws.rs (outbound signing) and
main.rs:306-347 (DNS backend discovery).  Signing is anchored against AWS's
published SigV4 example signatures; the e2e leg runs a local fake S3 server
that CRYPTOGRAPHICALLY verifies every forwarded request's signature.
"""

import datetime
import hashlib
import threading
import urllib.error
import urllib.request
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

import pyarrow as pa
import pytest

from lakesoul_tpu import LakeSoulCatalog
from lakesoul_tpu.service import sigv4
from lakesoul_tpu.service.jwt import Claims
from lakesoul_tpu.service.s3_upstream import DnsDiscovery, S3Upstream, S3UpstreamConfig
from lakesoul_tpu.service.storage_proxy import StorageProxy

SCHEMA = pa.schema([("id", pa.int64()), ("v", pa.float64())])
AK, SK = "AKIDEXAMPLE", "wJalrXUtnFEMI/K7MDENG+bPxRfiCYEXAMPLEKEY"


class TestSigV4Vectors:
    """AWS's published example signatures — byte-exact anchors."""

    def test_iam_list_users_example(self):
        # docs.aws.amazon.com "Signature Version 4 signing process" example
        headers = sigv4.sign_request(
            "GET",
            "iam.amazonaws.com",
            "/",
            "Action=ListUsers&Version=2010-05-08",
            {"content-type": "application/x-www-form-urlencoded; charset=utf-8"},
            sigv4.EMPTY_SHA256,
            access_key=AK,
            secret_key=SK,
            region="us-east-1",
            service="iam",
            timestamp=datetime.datetime(2015, 8, 30, 12, 36, 0),
        )
        assert headers["Authorization"] == (
            "AWS4-HMAC-SHA256 Credential=AKIDEXAMPLE/20150830/us-east-1/iam/"
            "aws4_request, SignedHeaders=content-type;host;x-amz-date, Signature="
            "5d672d79c15b13162d9279b0855cfba6789a8edb4c82c400e06b5924a6f2b5d7"
        )

    def test_s3_get_object_example(self):
        # the S3 "GET object with Range" documented example (NB: the S3 docs
        # use the slash variant of the example secret, the IAM docs the plus)
        headers = sigv4.sign_request(
            "GET",
            "examplebucket.s3.amazonaws.com",
            "/test.txt",
            "",
            {"range": "bytes=0-9"},
            sigv4.EMPTY_SHA256,
            access_key=AK,
            secret_key="wJalrXUtnFEMI/K7MDENG/bPxRfiCYEXAMPLEKEY",
            region="us-east-1",
            service="s3",
            timestamp=datetime.datetime(2013, 5, 24, 0, 0, 0),
        )
        assert headers["Authorization"].endswith(
            "Signature=f0e8bdb87c964420e857bd35b5d6ed310bd44f0170aba48dd91039c6036bdb41"
        )

    def test_verify_roundtrip_and_tamper(self):
        headers = sigv4.sign_request(
            "PUT", "s3.local:9000", "/bkt/a/b.parquet", "", {},
            hashlib.sha256(b"xyz").hexdigest(),
            access_key="AK1", secret_key="shh", region="eu-west-1",
        )
        ok = sigv4.verify_signature(
            "PUT", "/bkt/a/b.parquet", "", headers, secret_keys={"AK1": "shh"}
        )
        assert ok
        assert not sigv4.verify_signature(
            "PUT", "/bkt/a/OTHER", "", headers, secret_keys={"AK1": "shh"}
        )
        assert not sigv4.verify_signature(
            "PUT", "/bkt/a/b.parquet", "", headers, secret_keys={"AK1": "wrong"}
        )


class TestDnsDiscovery:
    def test_health_filter_and_round_robin(self):
        d = DnsDiscovery(
            "svc.local", 9000,
            resolver=lambda h, p: ["10.0.0.1", "10.0.0.2", "10.0.0.3"],
            health_check=lambda ip, p: ip != "10.0.0.2",
        )
        assert d.backends() == ["10.0.0.1", "10.0.0.3"]
        picks = {d.pick() for _ in range(4)}
        assert picks == {"10.0.0.1", "10.0.0.3"}

    def test_failure_markdown_and_recovery(self):
        now = [0.0]
        d = DnsDiscovery(
            "svc.local", 9000,
            resolver=lambda h, p: ["a", "b"],
            health_check=lambda ip, p: True,
            retry_down_s=10.0,
            clock=lambda: now[0],
        )
        d.report_failure("a")
        assert {d.pick() for _ in range(3)} == {"b"}
        now[0] = 11.0  # past retry window: "a" comes back
        assert {d.pick() for _ in range(4)} == {"a", "b"}

    def test_all_down_fails_open(self):
        d = DnsDiscovery(
            "svc.local", 9000,
            resolver=lambda h, p: ["a", "b"],
            health_check=lambda ip, p: True,
        )
        d.report_failure("a")
        d.report_failure("b")
        assert d.pick() in ("a", "b")  # degraded, not refusing service

    def test_refresh_interval_and_dns_change(self):
        now = [0.0]
        answers = [["a"], ["c", "d"]]
        d = DnsDiscovery(
            "svc.local", 9000,
            resolver=lambda h, p: answers[0 if now[0] < 30 else 1],
            health_check=lambda ip, p: True,
            refresh_interval_s=30.0,
            clock=lambda: now[0],
        )
        assert d.backends() == ["a"]
        now[0] = 5.0
        assert d.backends() == ["a"]  # cached within the interval
        now[0] = 31.0
        assert d.backends() == ["c", "d"]  # re-resolved


class FakeS3:
    """Minimal S3 endpoint verifying every request's SigV4 signature."""

    def __init__(self, access_key=AK, secret_key=SK):
        self.objects: dict[str, bytes] = {}
        self.bad_auth = 0
        store = self

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, *a):
                pass

            def _check(self) -> bool:
                path, _, query = self.path.partition("?")
                if not sigv4.verify_signature(
                    self.command, path, query, dict(self.headers),
                    secret_keys={access_key: secret_key},
                ):
                    store.bad_auth += 1
                    self.send_error(403, "SignatureDoesNotMatch")
                    return False
                return True

            def do_PUT(self):
                if not self._check():
                    return
                n = int(self.headers.get("Content-Length", 0))
                store.objects[self.path] = self.rfile.read(n)
                self.send_response(200)
                self.send_header("ETag", '"fake"')
                self.send_header("Content-Length", "0")
                self.end_headers()

            def do_GET(self):
                if not self._check():
                    return
                body = store.objects.get(self.path)
                if body is None:
                    self.send_error(404, "NoSuchKey")
                    return
                rng = self.headers.get("Range")
                status = 200
                if rng and rng.startswith("bytes="):
                    lo_s, _, hi_s = rng[6:].partition("-")
                    lo = int(lo_s)
                    hi = int(hi_s) + 1 if hi_s else len(body)
                    sliced = body[lo:hi]
                    status = 206
                    self.send_response(status)
                    self.send_header(
                        "Content-Range", f"bytes {lo}-{hi - 1}/{len(body)}"
                    )
                    body = sliced
                else:
                    self.send_response(status)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def do_HEAD(self):
                if not self._check():
                    return
                body = store.objects.get(self.path)
                if body is None:
                    self.send_error(404, "NoSuchKey")
                    return
                self.send_response(200)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()

        self.server = ThreadingHTTPServer(("127.0.0.1", 0), Handler)
        self.port = self.server.server_address[1]
        self.thread = threading.Thread(target=self.server.serve_forever, daemon=True)

    def start(self):
        self.thread.start()

    def stop(self):
        self.server.shutdown()
        self.server.server_close()


@pytest.fixture()
def fake_s3():
    s = FakeS3()
    s.start()
    yield s
    s.stop()


def _upstream(fake_s3, resolver=None, **kw) -> S3Upstream:
    cfg = S3UpstreamConfig(
        endpoint=f"http://s3.internal:{fake_s3.port}",
        bucket="lake",
        access_key=AK,
        secret_key=SK,
        connect_timeout_s=2.0,
        **kw,
    )
    return S3Upstream(
        cfg, resolver=resolver or (lambda h, p: ["127.0.0.1"]),
        health_check=lambda ip, p: True,
    )


class TestS3Upstream:
    def test_put_get_head_signed(self, fake_s3):
        up = _upstream(fake_s3)
        status, _, resp = up.request("PUT", "ns/t/file.bin", body=b"payload-123")
        resp.read()
        resp.close()
        assert status == 200
        assert fake_s3.objects["/lake/ns/t/file.bin"] == b"payload-123"
        status, headers, resp = up.request("GET", "ns/t/file.bin")
        got = resp.read()
        resp.close()
        assert status == 200 and got == b"payload-123"
        status, headers, resp = up.request(
            "GET", "ns/t/file.bin", range_header="bytes=2-4"
        )
        got = resp.read()
        resp.close()
        assert status == 206 and got == b"ylo"
        assert fake_s3.bad_auth == 0

    def test_failover_to_live_backend(self, fake_s3):
        # first backend refuses connections (127.0.0.2 same port, nothing
        # listening); the request reports it down and retries on the live one
        up = _upstream(fake_s3, resolver=lambda h, p: ["127.0.0.2", "127.0.0.1"])
        # force round robin to start on the dead backend
        for _ in range(4):
            status, _, resp = up.request("PUT", "k", body=b"x", retries=2)
            resp.read()
            resp.close()
            assert status == 200
        assert "127.0.0.2" in up.discovery._down_until


class TestProxyUpstreamE2E:
    """Client → RBAC/JWT proxy → SigV4-signed upstream → fake S3."""

    @pytest.fixture()
    def env(self, tmp_warehouse, fake_s3):
        catalog = LakeSoulCatalog(str(tmp_warehouse))
        catalog.create_table("t", SCHEMA)
        proxy = StorageProxy(
            catalog, jwt_secret="pxy", upstream=_upstream(fake_s3)
        )
        proxy.start()
        token = proxy.jwt_server.create_token(Claims(sub="u", group="public"))
        yield catalog, proxy, token, fake_s3
        proxy.stop()

    def _req(self, url, method="GET", token=None, data=None, rng=None):
        req = urllib.request.Request(url, method=method, data=data)
        if token:
            req.add_header("Authorization", f"Bearer {token}")
        if rng:
            req.add_header("Range", rng)
        return urllib.request.urlopen(req, timeout=5)

    def test_put_get_range_head_via_proxy(self, env):
        catalog, proxy, token, fake = env
        url = f"http://127.0.0.1:{proxy.port}/default/t/part-1.lsf"
        body = bytes(range(256)) * 4
        resp = self._req(url, method="PUT", token=token, data=body)
        assert resp.status == 200
        # the object landed on the upstream under the bucket prefix, and the
        # upstream verified the proxy's signature on every hop
        assert fake.objects["/lake/default/t/part-1.lsf"] == body
        assert fake.bad_auth == 0
        got = self._req(url, token=token).read()
        assert got == body
        r = self._req(url, token=token, rng="bytes=10-19")
        assert r.status == 206 and r.read() == body[10:20]
        h = self._req(url, method="HEAD", token=token)
        assert int(h.headers["Content-Length"]) == len(body)

    def test_escaped_key_signed_consistently(self, env):
        """Keys needing URI escaping must be encoded ONCE — the same form is
        signed and sent, or real S3 answers SignatureDoesNotMatch."""
        catalog, proxy, token, fake = env
        url = f"http://127.0.0.1:{proxy.port}/default/t/part%20a%2Bb.lsf"
        body = b"spaced-key-bytes"
        resp = self._req(url, method="PUT", token=token, data=body)
        assert resp.status == 200
        assert fake.bad_auth == 0
        stored = [k for k in fake.objects if "part" in k]
        assert stored == ["/lake/default/t/part%20a%2Bb.lsf"]
        got = self._req(url, token=token).read()
        assert got == body

    def test_rbac_still_enforced_before_upstream(self, env):
        catalog, proxy, token, fake = env
        url = f"http://127.0.0.1:{proxy.port}/default/t/x.bin"
        with pytest.raises(urllib.error.HTTPError) as e:
            self._req(url)  # no token: refused before any upstream traffic
        assert e.value.code == 401

    def test_missing_object_404(self, env):
        catalog, proxy, token, fake = env
        url = f"http://127.0.0.1:{proxy.port}/default/t/ghost"
        with pytest.raises(urllib.error.HTTPError) as e:
            self._req(url, token=token)
        assert e.value.code == 404
