"""Storage-proxy object-API coverage (VERDICT r4 missing #4 / weak #7).

The reference proxy passes EVERY S3 verb through RBAC
(rust/lakesoul-s3-proxy/src/main.rs:350) and its azure backend translates
ListObjectsV2 / multipart / batch-delete (azure.rs).  These tests pin the
proxy's DELETE, ListObjectsV2, and multipart-upload verbs — each behind the
same JWT+RBAC gate — and the cleaner running its destructive traffic
through the proxy instead of the store.
"""

import urllib.error
import urllib.request

import numpy as np
import pyarrow as pa
import pytest

from lakesoul_tpu import LakeSoulCatalog
from lakesoul_tpu.compaction.cleaner import Cleaner
from lakesoul_tpu.service.jwt import Claims
from lakesoul_tpu.service.storage_proxy import (
    ProxyDeleter,
    ProxyStorageClient,
    StorageProxy,
)

SCHEMA = pa.schema([("id", pa.int64()), ("v", pa.float64())])


@pytest.fixture()
def proxy_env(tmp_warehouse):
    catalog = LakeSoulCatalog(str(tmp_warehouse))
    t = catalog.create_table("t", SCHEMA)
    t.write_arrow(pa.table({"id": [1], "v": [1.0]}))
    proxy = StorageProxy(catalog, jwt_secret="pxy")
    proxy.start()
    token = proxy.jwt_server.create_token(Claims(sub="u", group="public"))
    client = ProxyStorageClient(f"http://127.0.0.1:{proxy.port}", token=token)
    yield catalog, proxy, token, t, client
    proxy.stop()


class TestDelete:
    def test_delete_removes_object(self, proxy_env):
        _, _, _, _, client = proxy_env
        client.put("default/t/junk.bin", b"x" * 100)
        assert client.head("default/t/junk.bin") == 100
        client.delete("default/t/junk.bin")
        with pytest.raises(OSError):
            client.head("default/t/junk.bin")

    def test_delete_is_idempotent(self, proxy_env):
        _, _, _, _, client = proxy_env
        client.delete("default/t/never-existed.bin")  # S3-style: no error

    def test_unauthorized_delete_rejected(self, proxy_env):
        catalog, proxy, token, _, _ = proxy_env
        catalog.client.create_table(
            "priv", f"{catalog.warehouse}/default/priv", SCHEMA, domain="teamZ"
        )
        client = ProxyStorageClient(f"http://127.0.0.1:{proxy.port}", token=token)
        with pytest.raises(PermissionError):
            client.delete("default/priv/data.parquet")
        # and with no credentials at all
        anon = ProxyStorageClient(f"http://127.0.0.1:{proxy.port}")
        with pytest.raises(PermissionError):
            anon.delete("default/t/x.bin")


class TestList:
    def test_list_objects_v2(self, proxy_env):
        _, _, _, t, client = proxy_env
        client.put("default/t/sub/a.bin", b"aa")
        client.put("default/t/sub/b.bin", b"bbb")
        keys = dict(client.list_objects("default/t"))
        assert keys["default/t/sub/a.bin"] == 2
        assert keys["default/t/sub/b.bin"] == 3
        # the committed parquet file shows up too
        assert any(k.endswith(".parquet") for k in keys)

    def test_list_prefix_filter(self, proxy_env):
        _, _, _, _, client = proxy_env
        client.put("default/t/x/one.bin", b"1")
        client.put("default/t/y/two.bin", b"2")
        keys = [k for k, _ in client.list_objects("default/t", prefix="x/")]
        assert keys == ["default/t/x/one.bin"]

    def test_list_requires_access(self, proxy_env):
        catalog, proxy, token, _, _ = proxy_env
        catalog.client.create_table(
            "priv2", f"{catalog.warehouse}/default/priv2", SCHEMA, domain="teamZ"
        )
        client = ProxyStorageClient(f"http://127.0.0.1:{proxy.port}", token=token)
        with pytest.raises(PermissionError):
            client.list_objects("default/priv2")


class TestMultipart:
    def test_multipart_round_trip(self, proxy_env):
        _, _, _, _, client = proxy_env
        key = "default/t/big.bin"
        upload = client.initiate_multipart(key)
        parts = [b"A" * 1000, b"B" * 500, b"C" * 250]
        # upload out of order: completion must assemble by part number
        client.upload_part(key, upload, 2, parts[1])
        client.upload_part(key, upload, 1, parts[0])
        client.upload_part(key, upload, 3, parts[2])
        client.complete_multipart(key, upload)
        assert client.get(key) == b"".join(parts)
        # staging directory is gone and invisible to list
        assert not any(".uploads" in k for k, _ in client.list_objects("default/t"))

    def test_abort_drops_parts(self, proxy_env):
        _, _, _, _, client = proxy_env
        key = "default/t/aborted.bin"
        upload = client.initiate_multipart(key)
        client.upload_part(key, upload, 1, b"zzz")
        client.abort_multipart(key, upload)
        with pytest.raises(OSError):
            client.head(key)
        assert not any(".uploads" in k for k, _ in client.list_objects("default/t"))

    def test_complete_unknown_upload_404(self, proxy_env):
        _, _, _, _, client = proxy_env
        with pytest.raises(OSError, match="404"):
            client.complete_multipart("default/t/nope.bin", "deadbeef")


class TestRangeStillWorks:
    def test_range_get_with_query_stripped(self, proxy_env):
        _, _, _, _, client = proxy_env
        client.put("default/t/r.bin", b"0123456789")
        assert client.get("default/t/r.bin", range_header="bytes=2-4") == b"234"


class TestCleanerThroughProxy:
    def test_cleaner_deletes_via_proxy(self, tmp_warehouse):
        import os

        catalog = LakeSoulCatalog(str(tmp_warehouse))
        t = catalog.create_table("c", SCHEMA, primary_keys=["id"], hash_bucket_num=1)
        t.write_arrow(pa.table({"id": [1], "v": [1.0]}))
        t.write_arrow(pa.table({"id": [2], "v": [2.0]}))
        old_files = [f for unit in t.scan().scan_plan() for f in unit.data_files]
        t.compact()
        proxy = StorageProxy(catalog, jwt_secret="pxy")
        proxy.start()
        try:
            token = proxy.jwt_server.create_token(Claims(sub="svc", group="public"))
            deleter = ProxyDeleter(
                catalog.warehouse,
                ProxyStorageClient(f"http://127.0.0.1:{proxy.port}", token=token),
            )
            cleaner = Cleaner(catalog, retention_ms=1, discard_grace_ms=1,
                              deleter=deleter)
            future = 10**14
            cleaner.clean_table("c", now_ms=future)
            assert cleaner.clean_discarded_files(now_ms=future) == len(old_files)
            for f in old_files:
                assert not os.path.exists(f)
            # table still reads from the compacted head
            got = t.to_arrow().sort_by("id")
            assert got.column("id").to_pylist() == [1, 2]
        finally:
            proxy.stop()

    def test_cleaner_through_proxy_respects_rbac(self, tmp_warehouse):
        """An under-privileged service identity cannot destroy data."""
        import os

        catalog = LakeSoulCatalog(str(tmp_warehouse))
        info = catalog.client.create_table(
            "priv", f"{catalog.warehouse}/default/priv", SCHEMA, domain="teamZ"
        )
        victim = f"{catalog.warehouse}/default/priv/data.bin"
        os.makedirs(os.path.dirname(victim), exist_ok=True)
        with open(victim, "wb") as f:
            f.write(b"precious")
        proxy = StorageProxy(catalog, jwt_secret="pxy")
        proxy.start()
        try:
            token = proxy.jwt_server.create_token(Claims(sub="svc", group="public"))
            deleter = ProxyDeleter(
                catalog.warehouse,
                ProxyStorageClient(f"http://127.0.0.1:{proxy.port}", token=token),
            )
            with pytest.raises(PermissionError):
                deleter(victim, None, missing_ok=True)
            assert os.path.exists(victim)
        finally:
            proxy.stop()
        del info

    def test_deleter_refuses_paths_outside_warehouse(self, tmp_warehouse):
        catalog = LakeSoulCatalog(str(tmp_warehouse))
        proxy = StorageProxy(catalog, jwt_secret="pxy")
        proxy.start()
        try:
            token = proxy.jwt_server.create_token(Claims(sub="svc", group="public"))
            deleter = ProxyDeleter(
                catalog.warehouse,
                ProxyStorageClient(f"http://127.0.0.1:{proxy.port}", token=token),
            )
            with pytest.raises(ValueError, match="outside the warehouse"):
                deleter("/etc/passwd", None)
        finally:
            proxy.stop()


class TestMultipartManifest:
    def test_manifest_selects_parts(self, proxy_env):
        """S3 semantics: the CompleteMultipartUpload body's manifest chooses
        which parts compose the object; unlisted parts are discarded."""
        import urllib.request

        _, proxy, token, _, client = proxy_env
        key = "default/t/manifested.bin"
        upload = client.initiate_multipart(key)
        client.upload_part(key, upload, 1, b"ONE")
        client.upload_part(key, upload, 2, b"TWO")
        client.upload_part(key, upload, 3, b"THREE")
        body = (
            b"<CompleteMultipartUpload>"
            b"<Part><PartNumber>1</PartNumber></Part>"
            b"<Part><PartNumber>3</PartNumber></Part>"
            b"</CompleteMultipartUpload>"
        )
        req = urllib.request.Request(
            f"http://127.0.0.1:{proxy.port}/{key}?uploadId={upload}",
            method="POST", data=body,
        )
        req.add_header("Authorization", f"Bearer {token}")
        urllib.request.urlopen(req, timeout=5)
        assert client.get(key) == b"ONETHREE"

    def test_manifest_missing_part_rejected(self, proxy_env):
        import urllib.error
        import urllib.request

        _, proxy, token, _, client = proxy_env
        key = "default/t/short.bin"
        upload = client.initiate_multipart(key)
        client.upload_part(key, upload, 1, b"X")
        body = (
            b"<CompleteMultipartUpload>"
            b"<Part><PartNumber>7</PartNumber></Part>"
            b"</CompleteMultipartUpload>"
        )
        req = urllib.request.Request(
            f"http://127.0.0.1:{proxy.port}/{key}?uploadId={upload}",
            method="POST", data=body,
        )
        req.add_header("Authorization", f"Bearer {token}")
        with pytest.raises(urllib.error.HTTPError) as e:
            urllib.request.urlopen(req, timeout=5)
        assert e.value.code == 400

    def test_part_upload_to_unknown_or_aborted_upload_404(self, proxy_env):
        """NoSuchUpload: parts for never-initiated or aborted uploads are
        rejected, never silently staged (abort-resurrection guard)."""
        _, _, _, _, client = proxy_env
        with pytest.raises(OSError, match="404"):
            client.upload_part("default/t/ghost.bin", "deadbeef", 1, b"x")
        key = "default/t/resurrect.bin"
        upload = client.initiate_multipart(key)
        client.abort_multipart(key, upload)
        with pytest.raises(OSError, match="404"):
            client.upload_part(key, upload, 1, b"x")
        with pytest.raises(OSError, match="404"):
            client.complete_multipart(key, upload)

    def test_failed_complete_leaves_upload_retryable(self, proxy_env):
        """S3 semantics: a failed CompleteMultipartUpload (missing part)
        leaves the upload OPEN — the client re-uploads the part and
        retries, instead of losing every uploaded byte."""
        import urllib.error
        import urllib.request

        _, proxy, token, _, client = proxy_env
        key = "default/t/retry.bin"
        upload = client.initiate_multipart(key)
        client.upload_part(key, upload, 1, b"ONE")
        body = (
            b"<CompleteMultipartUpload>"
            b"<Part><PartNumber>1</PartNumber></Part>"
            b"<Part><PartNumber>2</PartNumber></Part>"
            b"</CompleteMultipartUpload>"
        )
        req = urllib.request.Request(
            f"http://127.0.0.1:{proxy.port}/{key}?uploadId={upload}",
            method="POST", data=body,
        )
        req.add_header("Authorization", f"Bearer {token}")
        with pytest.raises(urllib.error.HTTPError) as e:
            urllib.request.urlopen(req, timeout=5)
        assert e.value.code == 400
        # upload still live: fix the gap and retry successfully
        client.upload_part(key, upload, 2, b"TWO")
        client.complete_multipart(key, upload)
        assert client.get(key) == b"ONETWO"
        # and only NOW is the id dead
        with pytest.raises(OSError, match="404"):
            client.complete_multipart(key, upload)


class TestListPaging:
    def test_continuation_token_pages_are_followed(self):
        """A real S3 upstream pages ListObjectsV2 at 1000 keys; the client
        must follow NextContinuationToken or silently truncate listings
        that ProxyDeleter/Cleaner act on destructively."""
        pages = [
            (
                b'<?xml version="1.0" encoding="UTF-8"?>'
                b'<ListBucketResult xmlns="http://s3.amazonaws.com/doc/2006-03-01/">'
                b"<IsTruncated>true</IsTruncated>"
                b"<NextContinuationToken>tok+1/=</NextContinuationToken>"
                b"<Contents><Key>ns/t/a.bin</Key><Size>1</Size></Contents>"
                b"</ListBucketResult>"
            ),
            (
                b'<?xml version="1.0" encoding="UTF-8"?>'
                b'<ListBucketResult xmlns="http://s3.amazonaws.com/doc/2006-03-01/">'
                b"<IsTruncated>false</IsTruncated>"
                b"<Contents><Key>ns/t/b.bin</Key><Size>2</Size></Contents>"
                b"</ListBucketResult>"
            ),
        ]
        queries = []
        client = ProxyStorageClient("http://127.0.0.1:1")

        def fake_request(method, key, *, body=None, query="", headers=None):
            queries.append(query)
            return 200, {}, pages[len(queries) - 1]

        client._request = fake_request
        out = client.list_objects("ns/t", prefix="p/")
        assert out == [("ns/t/a.bin", 1), ("ns/t/b.bin", 2)]
        assert "continuation-token" not in queries[0]
        # the token is echoed back fully URL-encoded on the second page
        assert "continuation-token=tok%2B1%2F%3D" in queries[1]
        assert all(q.startswith("list-type=2&prefix=p") for q in queries)


class TestPathTraversal:
    """_authorize must reject ''/./.. segments — raw AND percent-encoded —
    before _object_path/_object_key are built (cross-table DELETE/overwrite
    hole), and the multipart staging path gets the same treatment via the
    uploadId shape check."""

    def _raw(self, proxy, token, method, path, body=None):
        import http.client

        c = http.client.HTTPConnection("127.0.0.1", proxy.port, timeout=10)
        headers = {"Authorization": f"Bearer {token}"}
        if body is not None:
            headers["Content-Length"] = str(len(body))
        c.request(method, path, body=body, headers=headers)
        r = c.getresponse()
        r.read()
        c.close()
        return r.status

    def test_dotdot_segments_rejected(self, proxy_env):
        _, proxy, token, _, _ = proxy_env
        for path in (
            "/default/t/../../t2/file",
            "/default/t/./file",
            "/default/t//file",
            "/default/t/%2e%2e/t2/file",      # encoded '..'
            "/default/t/..%2Ft2%2Ffile",      # encoded '/' smuggled in a segment
        ):
            for method in ("DELETE", "PUT", "GET", "HEAD"):
                body = b"x" if method == "PUT" else None
                assert self._raw(proxy, token, method, path, body) == 400, (
                    method, path,
                )

    def test_legit_encoded_names_still_work(self, proxy_env):
        _, proxy, token, _, client = proxy_env
        assert self._raw(proxy, token, "PUT", "/default/t/part%20one.bin", b"hi") == 201
        assert client.get("default/t/part one.bin") == b"hi"

    def test_traversal_upload_id_never_touches_fs(self, proxy_env):
        _, proxy, token, _, _ = proxy_env
        evil = "..%2F..%2Fevil"
        status = self._raw(
            proxy, token, "PUT", f"/default/t/x.bin?partNumber=1&uploadId={evil}", b"x"
        )
        assert status == 404  # NoSuchUpload, no filesystem op
        assert self._raw(
            proxy, token, "POST", f"/default/t/x.bin?uploadId={evil}", b""
        ) == 404

    def test_part_number_range_enforced(self, proxy_env):
        _, proxy, token, _, client = proxy_env
        up = client.initiate_multipart("default/t/ranged.bin")
        for bad in ("0", "-3", "10001", "99999"):
            status = self._raw(
                proxy, token, "PUT",
                f"/default/t/ranged.bin?partNumber={bad}&uploadId={up}", b"x",
            )
            assert status == 400, bad
        client.upload_part("default/t/ranged.bin", up, 10000, b"ok")  # max legal
        client.abort_multipart("default/t/ranged.bin", up)
