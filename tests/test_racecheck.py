"""racecheck: the runtime Eraser detector must catch the seeded
shared-state race (with both access stacks), stay silent on locked and
init-phase writes, instrument/restore the hot classes cleanly, and its
ring canary must prove the ``LAKESOUL_COLLATE_REUSE`` contract — no slot
reused while a borrowed view is live — under the real loader with
prefetch + device prefetch, byte-identical to the ring-off run."""

from __future__ import annotations

import threading

import numpy as np
import pyarrow as pa
import pytest

from lakesoul_tpu.analysis import racecheck
from lakesoul_tpu.data.jax_iter import _BufferRing


@pytest.fixture()
def clean_racecheck():
    racecheck.reset()
    yield
    racecheck.disable()
    racecheck.reset()


# ------------------------------------------------------------ lockset core


def test_catches_seeded_unsynchronized_writes(clean_racecheck):
    from fixtures import racebugs

    with racecheck.watch() as w:
        racecheck.instrument_class(racebugs.UnsyncCounter)
        c = racebugs.unsynchronized_writes()
    assert c.value == 100  # instrumentation must not change behavior
    kinds = {v.kind for v in w.violations}
    assert kinds == {"shared-state-write"}
    v = w.violations[0]
    assert "UnsyncCounter.value" in v.message
    assert "no common lock" in v.message
    # both access stacks ship with the report: the first writer's and the
    # racing writer's — the evidence a torn update never leaves on its own
    assert len(v.stacks) == 2
    assert "first writer" in v.stacks[0]
    assert "racing writer" in v.stacks[1]


def test_silent_on_synchronized_writes(clean_racecheck):
    from fixtures import racebugs

    with racecheck.watch() as w:
        racecheck.instrument_class(racebugs.SyncCounter)
        c = racebugs.synchronized_writes()
    assert c.value == 100
    assert w.violations == [], "\n".join(v.render() for v in w.violations)


def test_silent_on_init_phase_then_locked_publish(clean_racecheck):
    """Eraser's Virgin→Exclusive: the constructing thread writes unlocked
    (construction happens-before publication); a second thread publishing
    under a lock afterwards is the sanctioned hand-off."""
    from fixtures import racebugs

    with racecheck.watch() as w:
        racecheck.instrument_class(racebugs.HandoffFlag)
        f = racebugs.locked_publish_after_init()
    assert f.fenced is True
    assert w.violations == [], "\n".join(v.render() for v in w.violations)


def test_lockset_refines_not_first_lock(clean_racecheck):
    """Two threads alternating two different locks share NO common lock —
    the intersection (not any single access) is what must be non-empty."""

    class TwoLocks:
        def __init__(self):
            self.a = threading.Lock()
            self.b = threading.Lock()
            self.field = 0

        def via_a(self):
            with self.a:
                self.field += 1

        def via_b(self):
            with self.b:
                self.field += 1

    with racecheck.watch() as w:
        racecheck.instrument_class(TwoLocks)
        obj = TwoLocks()
        for fn in (obj.via_a, obj.via_b):
            t = threading.Thread(target=fn)
            t.start()
            t.join()
    assert {v.kind for v in w.violations} == {"shared-state-write"}
    assert "TwoLocks.field" in w.violations[0].message


def test_instrumentation_restores_on_disable(clean_racecheck):
    from lakesoul_tpu.runtime.resilience import CircuitBreaker

    racecheck.enable()
    assert hasattr(CircuitBreaker.__dict__.get("__setattr__"), "_racecheck_orig")
    assert hasattr(_BufferRing.next_slot, "_racecheck_orig")
    racecheck.disable()
    assert "__setattr__" not in CircuitBreaker.__dict__ or not hasattr(
        CircuitBreaker.__dict__["__setattr__"], "_racecheck_orig"
    )
    assert not hasattr(_BufferRing.next_slot, "_racecheck_orig")


def test_hot_classes_run_clean_under_instrumentation(clean_racecheck):
    """The real resilience machinery (breaker under concurrent load) is the
    locked-discipline exemplar: zero violations."""
    from lakesoul_tpu.runtime.resilience import AdmissionController, CircuitBreaker

    with racecheck.watch() as w:
        breaker = CircuitBreaker("racecheck-probe", failure_threshold=2)
        gate = AdmissionController("racecheck-probe", max_inflight=2, max_queue=8)

        def hammer():
            for _ in range(50):
                try:
                    breaker.call(lambda: 1)
                except Exception:
                    pass
                with gate.admit():
                    pass

        threads = [threading.Thread(target=hammer) for _ in range(4)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
    assert w.violations == [], "\n".join(v.render() for v in w.violations)


def test_env_gate(monkeypatch):
    monkeypatch.delenv("LAKESOUL_RACECHECK", raising=False)
    assert not racecheck.env_requested()
    monkeypatch.setenv("LAKESOUL_RACECHECK", "1")
    assert racecheck.env_requested()


# ------------------------------------------------------------- ring canary


def test_ring_canary_detects_use_after_release(clean_racecheck):
    with racecheck.watch() as w:
        ring = _BufferRing(2)
        held = []
        for i in range(4):
            slot = ring.next_slot()
            if "c" not in slot:
                slot["c"] = np.zeros(8)
            held.append(slot["c"])  # borrower never lets go: contract broken
    kinds = {v.kind for v in w.violations}
    assert kinds == {"ring-use-after-release"}
    assert "borrowed view is still live" in w.violations[0].message


def test_ring_canary_poisons_released_slots(clean_racecheck):
    """A reused slot is poisoned at hand-out, so a stale read that slips
    past the refcount canary is loud garbage, not plausible data."""
    with racecheck.watch():
        ring = _BufferRing(1)
        slot = ring.next_slot()
        slot["c"] = np.zeros(8, dtype=np.float64)
        ring.next_slot()  # wrap: the slot is dead, its bytes poisoned
        assert all(b == 0xAB for b in slot["c"].view("uint8").tobytes())


def test_ring_canary_silent_for_conforming_borrower(clean_racecheck):
    with racecheck.watch() as w:
        ring = _BufferRing(2)
        for i in range(6):
            slot = ring.next_slot()
            if "c" not in slot:
                slot["c"] = np.zeros(8)
            slot["c"][...] = i  # fills and forgets, exactly one window
    assert w.violations == [], "\n".join(v.render() for v in w.violations)


# ----------------------------------------------- loader ring stress (e2e)


def _ring_table(tmp_warehouse, rows: int = 20_000):
    from lakesoul_tpu import LakeSoulCatalog

    catalog = LakeSoulCatalog(str(tmp_warehouse))
    schema = pa.schema([("id", pa.int64()), ("v", pa.float64())])
    t = catalog.create_table("ring_stress", schema)
    rng = np.random.default_rng(7)
    t.write_arrow(pa.table({
        "id": np.arange(rows, dtype=np.int64),
        "v": rng.normal(size=rows),
    }, schema=schema))
    return t


def test_collate_reuse_ring_stress_canary_and_byte_identity(
    tmp_warehouse, monkeypatch, clean_racecheck
):
    """The satellite proof: under ``prefetch + device_prefetch`` with the
    reuse ring ON and the canary ARMED, a conforming consumer (device_put
    copies each batch out) triggers zero use-after-release across multiple
    epochs, and the delivered values are byte-identical to the ring-off
    run."""
    t = _ring_table(tmp_warehouse)
    baseline = [
        {k: np.copy(v) for k, v in b.items()}
        for b in t.scan().batch_size(256).to_jax_iter(
            device_put=False, prefetch=4, drop_remainder=False
        )
    ]

    monkeypatch.setenv("LAKESOUL_COLLATE_REUSE", "1")
    with racecheck.watch() as w:
        # host leg: ring on, conforming copy-out — BYTE-identical to ring-off
        it = t.scan().batch_size(256).to_jax_iter(
            device_put=False, prefetch=4, drop_remainder=False
        )
        assert it._ring is not None
        got = [{k: np.copy(v) for k, v in b.items()} for b in it]
        assert len(got) == len(baseline)
        for a, b in zip(got, baseline):
            assert a.keys() == b.keys()
            for k in a:
                assert a[k].tobytes() == b[k].tobytes(), k
        # device leg under prefetch + device_prefetch: the disarm condition
        # keys on MEASURED aliasing (tensorplane delivery_copies probe) —
        # THIS table's columns are int64/float64, which the host backend
        # demotes to 32-bit on device_put, so every put is a REAL copy and
        # the ring stays ARMED even on CPU (the PR-9 platform guess kept it
        # down); the canary proves the copies finish before slot reuse and
        # device dtypes are the 32-bit demotions, so compare after the
        # deterministic cast
        for _ in range(2):
            it = t.scan().batch_size(256).to_jax_iter(
                device_put=True, prefetch=4, device_prefetch=2,
                drop_remainder=False,
            )
            assert it._ring is not None  # every column's put is a real copy
            dev = [{k: np.asarray(v) for k, v in b.items()} for b in it]
            assert len(dev) == len(baseline)
            for a, b in zip(dev, baseline):
                for k in a:
                    assert np.array_equal(a[k], b[k].astype(a[k].dtype)), k
    assert w.violations == [], "\n".join(v.render() for v in w.violations)


def test_collate_reuse_ring_disarms_on_measured_aliasing(
    tmp_warehouse, monkeypatch, clean_racecheck
):
    """The other half of the probe-keyed contract: a table with a
    device-dtype (float32) column CAN alias on a host backend — device_put
    zero-copies aligned dtype-matching buffers — so the loader must still
    refuse to arm the ring there (the original PR-9 aliased-overwrite
    find, now pinned through the measurement instead of the platform)."""
    from lakesoul_tpu import LakeSoulCatalog
    from lakesoul_tpu.tensorplane.dlpack import device_put_copies

    catalog = LakeSoulCatalog(str(tmp_warehouse))
    schema = pa.schema([("id", pa.int64()), ("v", pa.float32())])
    t = catalog.create_table("ring_alias", schema)
    rng = np.random.default_rng(11)
    t.write_arrow(pa.table({
        "id": np.arange(4_000, dtype=np.int64),
        "v": rng.normal(size=4_000).astype(np.float32),
    }, schema=schema))
    assert not device_put_copies(np.float32)  # the measured premise (CPU CI)
    assert device_put_copies(np.int64)  # demotion = real copy
    monkeypatch.setenv("LAKESOUL_COLLATE_REUSE", "1")
    it = t.scan().batch_size(256).to_jax_iter(
        device_put=True, prefetch=4, device_prefetch=2, drop_remainder=False
    )
    assert it._ring is None  # one aliasing column disarms the whole ring
    # host-consumer loaders keep the old contract (consumer copies out)
    it2 = t.scan().batch_size(256).to_jax_iter(device_put=False)
    assert it2._ring is not None
    list(it)
    list(it2)


def test_collate_reuse_ring_stress_catches_hoarding_consumer(
    tmp_warehouse, monkeypatch, clean_racecheck
):
    """The adversarial twin: a consumer that KEEPS every delivered host
    batch holds borrowed views past the ring wrap — the canary must call
    it out (this is the silent-corruption case without racecheck)."""
    t = _ring_table(tmp_warehouse, rows=8_000)
    monkeypatch.setenv("LAKESOUL_COLLATE_REUSE", "1")
    with racecheck.watch() as w:
        it = t.scan().batch_size(256).to_jax_iter(
            device_put=False, prefetch=4, drop_remainder=False
        )
        assert it._ring is not None
        hoard = list(it)  # every batch kept: contract broken
    assert len(hoard) > 0
    assert any(v.kind == "ring-use-after-release" for v in w.violations)
