"""Chaos suite for the resilience layer (runtime/resilience.py).

Proves the failure-mode guarantees the subsystem exists for:

- RetryPolicy: deterministic seeded backoff, taxonomy-driven classification,
  total deadline, obs counters.
- CircuitBreaker: closed/open/half-open transitions on an injected clock.
- AdmissionController: bounded in-flight + queue, typed OverloadedError.
- Scans under p=0.3 injected transient object-store faults return
  byte-identical batches vs a clean run (retries absorb the chaos).
- A writer killed mid-commit (between metadata phase 1 and phase 2) leaves
  no partial state visible, and the next catalog open rolls the commit
  forward (staged files intact) or back (staged files lost).
- 64 concurrent ANN clients against a full admission queue get typed
  rejections with bounded queue depth and p50/p99 latency in the obs
  registry; the Flight gateway maps the shed to UNAVAILABLE.
- FaultSpec parsing edge cases and clear()-vs-env semantics.
"""

from __future__ import annotations

import os
import signal
import subprocess
import sys
import textwrap
import threading
import time

import numpy as np
import pyarrow as pa
import pytest

from lakesoul_tpu import LakeSoulCatalog
from lakesoul_tpu.errors import (
    CircuitOpenError,
    ConfigError,
    OverloadedError,
    RBACError,
)
from lakesoul_tpu.meta.client import MetaDataClient
from lakesoul_tpu.obs import registry
from lakesoul_tpu.runtime import faults
from lakesoul_tpu.runtime.faults import FaultInjected, FaultSpec
from lakesoul_tpu.runtime.resilience import (
    AdmissionController,
    CircuitBreaker,
    RetryPolicy,
    is_transient,
)

SCHEMA = pa.schema([("id", pa.int64()), ("v", pa.float64())])


@pytest.fixture(autouse=True)
def _clean_faults():
    faults.clear()
    yield
    faults.clear()


def _counter(name_with_labels: str) -> float:
    return registry().snapshot().get(name_with_labels, 0)


# ------------------------------------------------------------------ taxonomy


class TestTaxonomy:
    def test_transient_families(self):
        assert is_transient(ConnectionError("blip"))
        assert is_transient(TimeoutError())
        assert is_transient(OSError("socket reset"))
        assert is_transient(FaultInjected("chaos"))
        assert is_transient(OverloadedError("shed"))

    def test_permanent_families(self):
        assert not is_transient(FileNotFoundError("gone"))
        assert not is_transient(PermissionError("denied"))
        assert not is_transient(ValueError("bad input"))
        assert not is_transient(ConfigError("bad knob"))
        assert not is_transient(RBACError("no"))
        # retrying through an open breaker would defeat the breaker
        assert not is_transient(CircuitOpenError("open"))


# --------------------------------------------------------------- RetryPolicy


class TestRetryPolicy:
    def test_delays_are_deterministic_per_seed(self):
        a = RetryPolicy(max_attempts=5, seed=7).delays()
        b = RetryPolicy(max_attempts=5, seed=7).delays()
        c = RetryPolicy(max_attempts=5, seed=8).delays()
        assert a == b
        assert a != c
        assert len(a) == 4
        # exponential shape under the jitter envelope
        base = RetryPolicy(max_attempts=5, seed=7)
        for i, d in enumerate(a):
            lo = min(base.max_delay_s, base.base_delay_s * base.multiplier**i)
            assert lo <= d <= lo * (1 + base.jitter)

    def test_transient_retries_then_succeeds(self):
        calls = []

        def flappy():
            calls.append(1)
            if len(calls) < 3:
                raise ConnectionError("blip")
            return "ok"

        before = _counter('lakesoul_retry_attempts_total{op="t.flappy"}')
        out = RetryPolicy(max_attempts=5, base_delay_s=0.0, jitter=0.0).run(
            flappy, op="t.flappy"
        )
        assert out == "ok" and len(calls) == 3
        assert _counter('lakesoul_retry_attempts_total{op="t.flappy"}') == before + 2

    def test_permanent_error_raises_immediately(self):
        calls = []

        def broken():
            calls.append(1)
            raise ValueError("permanent")

        with pytest.raises(ValueError):
            RetryPolicy(max_attempts=5, base_delay_s=0.0).run(broken, op="t.perm")
        assert len(calls) == 1

    def test_exhaustion_raises_last_and_counts(self):
        before = _counter('lakesoul_retry_exhausted_total{op="t.exhaust"}')

        def dead():
            raise ConnectionError("still down")

        with pytest.raises(ConnectionError, match="still down"):
            RetryPolicy(max_attempts=3, base_delay_s=0.0, jitter=0.0).run(
                dead, op="t.exhaust"
            )
        assert _counter('lakesoul_retry_exhausted_total{op="t.exhaust"}') == before + 1

    def test_total_deadline_cuts_backoff_short(self):
        sleeps = []

        def dead():
            raise ConnectionError("down")

        with pytest.raises(ConnectionError):
            RetryPolicy(
                max_attempts=10, base_delay_s=5.0, jitter=0.0, total_deadline_s=0.01
            ).run(dead, op="t.deadline", sleep=sleeps.append)
        assert sleeps == []  # the first 5 s backoff would cross the deadline

    def test_attempt_timeout_reaches_callable(self):
        seen = []

        def probe(timeout=None):
            seen.append(timeout)
            return "ok"

        RetryPolicy(max_attempts=2, attempt_timeout_s=1.5).run(probe, op="t.budget")
        assert seen == [1.5]

    def test_from_env_overrides(self, monkeypatch):
        monkeypatch.setenv("LAKESOUL_RETRY_MAX_ATTEMPTS", "7")
        monkeypatch.setenv("LAKESOUL_RETRY_BASE_S", "0.25")
        monkeypatch.setenv("LAKESOUL_RETRY_SEED", "42")
        p = RetryPolicy.from_env()
        assert p.max_attempts == 7 and p.base_delay_s == 0.25 and p.seed == 42
        q = RetryPolicy.from_env(max_attempts=2)
        assert q.max_attempts == 2 and q.base_delay_s == 0.25

    def test_default_seed_decorrelates_threads(self, monkeypatch):
        # unset env seed → competing retriers must NOT share a backoff
        # schedule (two writers losing the same commit race would otherwise
        # collide again on every attempt), while each thread's own schedule
        # stays deterministic
        monkeypatch.delenv("LAKESOUL_RETRY_SEED", raising=False)
        policy = RetryPolicy.from_env(max_attempts=6)
        assert policy.seed is None
        schedules: dict[int, tuple] = {}
        # both threads must be ALIVE simultaneously: thread idents are
        # reused after death, and a reused ident would legitimately share
        # the schedule
        barrier = threading.Barrier(2)

        def grab(k):
            barrier.wait()
            schedules[k] = (tuple(policy.delays()), tuple(policy.delays()))

        threads = [threading.Thread(target=grab, args=(i,)) for i in range(2)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        a, b = schedules[0], schedules[1]
        assert a[0] == a[1] and b[0] == b[1]  # per-thread deterministic
        assert a[0] != b[0]  # decorrelated across threads


# ------------------------------------------------------------ CircuitBreaker


class TestCircuitBreaker:
    def test_state_machine(self):
        now = [0.0]
        b = CircuitBreaker(
            "t.breaker", failure_threshold=2, reset_timeout_s=10.0,
            clock=lambda: now[0],
        )
        assert b.state == CircuitBreaker.CLOSED and b.allow()
        b.record_failure()
        assert b.state == CircuitBreaker.CLOSED  # below threshold
        b.record_failure()
        assert b.state == CircuitBreaker.OPEN
        assert not b.allow()
        assert b.open_until() == pytest.approx(10.0)
        with pytest.raises(CircuitOpenError):
            b.call(lambda: "nope")
        # reset timeout passes → half-open admits one probe
        now[0] = 11.0
        assert b.state == CircuitBreaker.HALF_OPEN
        assert b.allow()        # the probe slot
        assert not b.allow()    # concurrent second probe is rejected
        b.record_success()
        assert b.state == CircuitBreaker.CLOSED
        # a half-open probe FAILURE re-opens for another timeout
        b.record_failure()
        b.record_failure()
        now[0] = 22.0
        assert b.state == CircuitBreaker.HALF_OPEN
        b.record_failure()
        assert b.state == CircuitBreaker.OPEN

    def test_state_gauge_published(self):
        b = CircuitBreaker("t.gauge", failure_threshold=1, reset_timeout_s=99.0)
        b.record_failure()
        assert _counter('lakesoul_circuit_state{circuit="t.gauge"}') == 1
        b.record_success()
        assert _counter('lakesoul_circuit_state{circuit="t.gauge"}') == 0


# ------------------------------------------- AdmissionController (unit level)


class TestAdmissionController:
    def test_rejects_beyond_queue_and_recovers(self):
        gate = AdmissionController(
            "t.gate", max_inflight=1, max_queue=1, queue_timeout_s=5.0
        )
        gate.acquire()  # slot taken
        queued_in = threading.Event()
        admitted = threading.Event()

        def queued_caller():
            queued_in.set()
            with gate.admit():
                admitted.set()

        t = threading.Thread(target=queued_caller)
        t.start()
        queued_in.wait(2.0)
        deadline = time.monotonic() + 2.0
        while gate.snapshot()["waiting"] < 1 and time.monotonic() < deadline:
            time.sleep(0.005)
        assert gate.snapshot()["waiting"] == 1
        # queue full: the next caller is shed with a typed error, now
        before = _counter('lakesoul_admission_rejected_total{gate="t.gate"}')
        with pytest.raises(OverloadedError):
            gate.acquire()
        assert _counter('lakesoul_admission_rejected_total{gate="t.gate"}') == before + 1
        # releasing the slot admits the queued caller
        gate.release()
        assert admitted.wait(2.0)
        t.join(2.0)
        snap = gate.snapshot()
        assert snap["inflight"] == 0 and snap["waiting"] == 0

    def test_queue_wait_timeout_is_typed(self):
        gate = AdmissionController(
            "t.gate2", max_inflight=1, max_queue=4, queue_timeout_s=0.05
        )
        gate.acquire()
        started = time.monotonic()
        with pytest.raises(OverloadedError, match="queued"):
            gate.acquire()
        assert time.monotonic() - started < 2.0
        gate.release()


# ------------------------------------------------------- FaultSpec edge cases


class TestFaultSpecParsing:
    def test_new_kinds_parse(self):
        assert FaultSpec.parse("s:0.5:flaky").kind == "flaky"
        hang = FaultSpec.parse("s:1:hang")
        assert hang.kind == "hang" and hang.seconds == 5.0
        trunc = FaultSpec.parse("s:1:truncate:0.25")
        assert trunc.kind == "truncate" and trunc.seconds == 0.25
        assert FaultSpec.parse("s:1:truncate").seconds == 0.5

    def test_bad_probability(self):
        with pytest.raises(ValueError, match="not a number"):
            FaultSpec.parse("s:abc")
        with pytest.raises(ValueError, match="bad fault spec"):
            FaultSpec.parse("s:1.5")
        with pytest.raises(ValueError, match="bad fault spec"):
            FaultSpec.parse("s:-0.1")

    def test_unknown_kind(self):
        with pytest.raises(ValueError, match="fault kind"):
            FaultSpec.parse("s:0.5:explode")

    def test_empty_stage(self):
        with pytest.raises(ValueError, match="bad fault spec"):
            FaultSpec.parse(":0.5")

    def test_missing_probability(self):
        with pytest.raises(ValueError, match="must be stage:probability"):
            FaultSpec.parse("stageonly")

    def test_bad_seconds_and_truncate_fraction(self):
        with pytest.raises(ValueError, match="not a number"):
            FaultSpec.parse("s:1:delay:soon")
        with pytest.raises(ValueError, match="keep-fraction"):
            FaultSpec.parse("s:1:truncate:1.5")

    def test_clear_does_not_resurrect_env_specs(self, monkeypatch):
        monkeypatch.setenv("LAKESOUL_FAULTS", "envstage:1.0")
        monkeypatch.setattr(faults, "_ENV_LOADED", False)
        monkeypatch.setattr(faults, "_SPECS", [])
        monkeypatch.setattr(faults, "_ENABLED", False)
        assert [s.stage for s in faults.active()] == ["envstage"]
        faults.clear()
        # the env var is still set, but a cleared config stays cleared
        assert faults.active() == []
        faults.maybe_inject("envstage")  # must not raise

    def test_truncate_only_acts_on_bytes(self):
        faults.install("chop:1.0:truncate:0.5")
        faults.maybe_inject("chop")  # control-flow path: no effect
        assert faults.filter_bytes("chop", b"12345678") == b"1234"
        assert faults.filter_bytes("other", b"12345678") == b"12345678"


# -------------------------------------------------- chaos: object-store scans


class TestChaosScan:
    @pytest.fixture()
    def mem_table(self, tmp_path, monkeypatch):
        # generous attempts so p=0.3 per-call chaos is absorbed with margin;
        # tiny backoff keeps the test fast
        monkeypatch.setenv("LAKESOUL_RETRY_MAX_ATTEMPTS", "10")
        monkeypatch.setenv("LAKESOUL_RETRY_BASE_S", "0.001")
        monkeypatch.setenv("LAKESOUL_RETRY_CAP_S", "0.005")
        catalog = LakeSoulCatalog(
            "memory://chaos-wh", db_path=str(tmp_path / "meta.db")
        )
        t = catalog.create_table("chaos", SCHEMA)
        rng = np.random.default_rng(0)
        for i in range(6):
            t.write_arrow(pa.table({
                "id": np.arange(i * 1000, (i + 1) * 1000),
                "v": rng.normal(size=1000),
            }, schema=SCHEMA))
        return t

    def test_scan_under_transient_faults_is_byte_identical(self, mem_table):
        clean = list(mem_table.scan().batch_size(2048).to_batches())
        assert sum(len(b) for b in clean) == 6000
        before_attempts = _counter(
            'lakesoul_retry_attempts_total{op="object_store.open"}'
        ) + _counter('lakesoul_retry_attempts_total{op="object_store.info"}')
        faults.install("object_store.open:0.3:flaky")
        faults.install("object_store.info:0.3:flaky")
        faulted = list(mem_table.scan().batch_size(2048).to_batches())
        assert len(faulted) == len(clean)
        for a, b in zip(clean, faulted):
            assert a.equals(b)  # byte-identical despite injected chaos
        after_attempts = _counter(
            'lakesoul_retry_attempts_total{op="object_store.open"}'
        ) + _counter('lakesoul_retry_attempts_total{op="object_store.info"}')
        assert after_attempts > before_attempts  # the chaos really fired

    def test_truncated_reads_detected_and_exhausted(self, mem_table, monkeypatch):
        monkeypatch.setenv("LAKESOUL_RETRY_MAX_ATTEMPTS", "2")
        from lakesoul_tpu.io.object_store import filesystem_for

        fs, p = filesystem_for("memory://chaos-wh/blob.bin")
        fs.pipe_file(p, b"x" * 1024)
        assert fs.cat_file(p) == b"x" * 1024
        faults.install("object_store.cat_file:1.0:truncate:0.5")
        # every attempt comes back short → detected (never returned) and,
        # with the fault permanent, surfaced as the transient it models
        with pytest.raises(ConnectionError, match="short read"):
            fs.cat_file(p)

    def test_flaky_cat_file_absorbed(self, mem_table):
        from lakesoul_tpu.io.object_store import filesystem_for

        fs, p = filesystem_for("memory://chaos-wh/blob2.bin")
        fs.pipe_file(p, b"payload")
        faults.install("object_store.cat_file:0.5:flaky")
        for _ in range(8):
            assert fs.cat_file(p) == b"payload"

    def test_real_short_read_detected_and_retried(self, mem_table):
        # a body cut mid-flight (not injected: the backend itself returns
        # short for a range fully inside the object) must be detected by
        # length and absorbed by a retry, never returned to the decoder
        import fsspec

        from lakesoul_tpu.io.object_store import ResilientFileSystem
        from lakesoul_tpu.runtime.resilience import RetryPolicy

        mem = fsspec.filesystem("memory")
        mem.pipe_file("/sr/blob", b"x" * 1024)

        class _CutOnce:
            def __init__(self, inner):
                self.inner = inner
                self.cuts = 0

            def cat_file(self, path, start=None, end=None, **kw):
                out = self.inner.cat_file(path, start=start, end=end, **kw)
                if self.cuts == 0:
                    self.cuts += 1
                    return out[: len(out) // 2]  # dropped connection mid-body
                return out

            def __getattr__(self, name):
                return getattr(self.inner, name)

        fs = ResilientFileSystem(
            _CutOnce(mem), RetryPolicy(max_attempts=3, base_delay_s=0.0)
        )
        assert fs.cat_file("/sr/blob", start=0, end=512) == b"x" * 512
        assert fs.target.cuts == 1  # the short body really happened
        # a range overrunning EOF is legitimately short — no false positive
        assert fs.cat_file("/sr/blob", start=1000, end=2048) == b"x" * 24
        mem.rm("/sr", recursive=True)

    def test_page_cache_fetch_fault_absorbed_in_stacked_config(
        self, tmp_path, monkeypatch
    ):
        # `page_cache.fetch` chaos must be policy-absorbed in BOTH cache
        # constructions: raw target (unit tests) and the production stack
        # where CachedReadFileSystem sits above a ResilientFileSystem
        import fsspec

        from lakesoul_tpu.io.object_store import ResilientFileSystem
        from lakesoul_tpu.io.page_cache import DiskPageCache

        monkeypatch.setenv("LAKESOUL_RETRY_MAX_ATTEMPTS", "10")
        monkeypatch.setenv("LAKESOUL_RETRY_BASE_S", "0.001")
        monkeypatch.setenv("LAKESOUL_RETRY_CAP_S", "0.005")
        mem = fsspec.filesystem("memory")
        data = bytes(range(256)) * 512  # 128 KiB
        mem.pipe_file("/rz/blob", data)
        try:
            faults.install("page_cache.fetch:0.4:flaky")
            raw = DiskPageCache(str(tmp_path / "raw"), page_bytes=16 << 10)
            assert raw.read_range(mem, "/rz/blob", 0, len(data)) == data
            stacked_fs = ResilientFileSystem(mem, RetryPolicy.from_env())
            stacked = DiskPageCache(str(tmp_path / "st"), page_bytes=16 << 10)
            assert (
                stacked.read_range(stacked_fs, "/rz/blob", 0, len(data)) == data
            )
        finally:
            mem.rm("/rz", recursive=True)


# -------------------------------------------- chaos: kill-subprocess-mid-commit

_CHILD_SCRIPT = textwrap.dedent(
    """
    import sys
    import numpy as np
    import pyarrow as pa
    from lakesoul_tpu import LakeSoulCatalog

    wh, db = sys.argv[1], sys.argv[2]
    catalog = LakeSoulCatalog(wh, db_path=db)
    t = catalog.table("t")
    t.write_arrow(pa.table({
        "id": np.arange(100, 110, dtype=np.int64),
        "v": np.full(10, 7.0),
    }))
    print("COMMITTED", flush=True)   # never reached: phase 2 hangs
    """
)


class TestKillMidCommit:
    def _spawn_and_kill_mid_commit(self, tmp_path, wh, db):
        """Run a writer child that hangs between commit phase 1 and phase 2,
        wait until its phase-1 rows are durable, then SIGKILL it."""
        script = tmp_path / "child_writer.py"
        script.write_text(_CHILD_SCRIPT)
        env = dict(os.environ)
        env.update({
            "JAX_PLATFORMS": "cpu",
            "PYTHONPATH": str(os.path.dirname(os.path.dirname(__file__))),
            # hang INSIDE commit_data, after phase 1 inserted the commit rows
            "LAKESOUL_FAULTS": "meta.commit.phase2:1:hang:120",
        })
        proc = subprocess.Popen(
            [sys.executable, str(script), wh, db],
            env=env, stdout=subprocess.PIPE, stderr=subprocess.PIPE,
        )
        try:
            probe = MetaDataClient(db_path=db)
            deadline = time.monotonic() + 60.0
            while time.monotonic() < deadline:
                if probe.store.list_uncommitted_commits():
                    break
                if proc.poll() is not None:
                    out, err = proc.communicate()
                    raise AssertionError(
                        f"child exited early: {out!r} {err!r}"
                    )
                time.sleep(0.05)
            else:
                raise AssertionError("child never reached phase 1")
        finally:
            proc.send_signal(signal.SIGKILL)
            proc.wait(10.0)

    def test_kill_mid_commit_rolls_forward_on_next_open(
        self, tmp_path, monkeypatch
    ):
        wh = str(tmp_path / "wh")
        db = str(tmp_path / "meta.db")
        catalog = LakeSoulCatalog(wh, db_path=db)
        t = catalog.create_table("t", SCHEMA)
        t.write_arrow(pa.table({
            "id": np.arange(10, dtype=np.int64), "v": np.zeros(10),
        }, schema=SCHEMA))

        self._spawn_and_kill_mid_commit(tmp_path, wh, db)

        # consistency BEFORE recovery: the half-commit is invisible — scans
        # see exactly the pre-crash rows, never a partial batch
        fresh = MetaDataClient(db_path=db)
        dangling = fresh.store.list_uncommitted_commits()
        assert len(dangling) == 1
        plan_files = [
            f
            for u in fresh.get_scan_plan_partitions("t")
            for f in u.data_files
        ]
        staged = [op.path for c in dangling for op in c.file_ops]
        assert staged and not set(staged) & set(plan_files)

        # next open (sweep age 0) detects the interrupted commit and rolls
        # it FORWARD — the staged files are intact and become visible
        monkeypatch.setenv("LAKESOUL_RECOVER_MIN_AGE_MS", "0")
        reopened = LakeSoulCatalog(wh, db_path=db)
        recovered = reopened.table("t").to_arrow()
        ids = sorted(recovered.column("id").to_pylist())
        assert ids == list(range(10)) + list(range(100, 110))
        assert reopened.client.store.list_uncommitted_commits() == []

    def test_kill_mid_commit_rolls_back_when_staged_files_lost(
        self, tmp_path, monkeypatch
    ):
        wh = str(tmp_path / "wh")
        db = str(tmp_path / "meta.db")
        catalog = LakeSoulCatalog(wh, db_path=db)
        t = catalog.create_table("t", SCHEMA)
        t.write_arrow(pa.table({
            "id": np.arange(10, dtype=np.int64), "v": np.zeros(10),
        }, schema=SCHEMA))

        self._spawn_and_kill_mid_commit(tmp_path, wh, db)

        fresh = MetaDataClient(db_path=db)
        dangling = fresh.store.list_uncommitted_commits()
        assert len(dangling) == 1
        for c in dangling:
            for op in c.file_ops:
                os.remove(op.path)  # the staged data is gone for good
        counts = fresh.recover_incomplete_commits(min_age_ms=0)
        assert counts["rolled_back"] == 1 and counts["rolled_forward"] == 0
        assert fresh.store.list_uncommitted_commits() == []
        # the table still serves exactly its pre-crash content
        reopened = LakeSoulCatalog(wh, db_path=db)
        ids = sorted(reopened.table("t").to_arrow().column("id").to_pylist())
        assert ids == list(range(10))

    def test_flag_only_crash_is_repaired(self, tmp_path):
        """Crash signature 3: phase 2 ran but the committed flag never
        flipped — recovery repairs the flag without re-committing."""
        db = str(tmp_path / "meta.db")
        catalog = LakeSoulCatalog(str(tmp_path / "wh"), db_path=db)
        t = catalog.create_table("t", SCHEMA)
        t.write_arrow(pa.table({
            "id": np.arange(5, dtype=np.int64), "v": np.zeros(5),
        }, schema=SCHEMA))
        client = catalog.client
        # simulate the crash window by un-flipping the flag
        with client.store.transaction() as conn:
            client.store._exec(conn, "UPDATE data_commit_info SET committed=0")
        counts = client.recover_incomplete_commits(min_age_ms=0)
        assert counts["flag_repaired"] == 1
        assert client.store.list_uncommitted_commits() == []
        assert t.to_arrow().num_rows == 5


# ------------------------------------------- overload: 64 concurrent clients


def _histogram_percentile(series: dict, q: float) -> float:
    """Percentile estimate from a registry histogram snapshot
    ({buckets: {bound: cumulative}, count, sum})."""
    count = series["count"]
    assert count > 0
    rank = q * count
    for bound, cum in sorted(series["buckets"].items()):
        if cum >= rank:
            return bound
    return float("inf")


class _SlowIndex:
    """Stand-in ANN index: fixed per-batch latency, deterministic result."""

    class config:
        dim = 4

    def batch_search(self, queries, params):
        time.sleep(0.02)
        n = len(queries)
        return np.tile(np.arange(3), (n, 1)), np.zeros((n, 3), dtype=np.float32)


class TestOverload:
    def test_64_concurrent_clients_bounded_queue_typed_rejections(self):
        from lakesoul_tpu.vector.serving import AnnEndpoint

        before = registry().snapshot().get(
            'lakesoul_ann_request_seconds{endpoint="default"}', {"count": 0}
        )["count"]
        ep = AnnEndpoint(
            _SlowIndex(), max_batch=4, max_wait_ms=1.0, max_pending=8
        )
        results = {"ok": 0, "shed": 0}
        res_guard = threading.Lock()
        start_gate = threading.Event()

        def client():
            start_gate.wait()
            try:
                fut = ep.submit(np.zeros(4, dtype=np.float32))
                ids, dists = fut.result(timeout=30.0)
                assert list(ids) == [0, 1, 2]
                with res_guard:
                    results["ok"] += 1
            except OverloadedError:
                with res_guard:
                    results["shed"] += 1

        threads = [threading.Thread(target=client) for _ in range(64)]
        for t in threads:
            t.start()
        start_gate.set()
        for t in threads:
            t.join(60.0)
        try:
            stats = ep.stats()
            # every client got a definitive answer: result or typed shed —
            # and the queue never grew past its bound (no unbounded backlog)
            assert results["ok"] + results["shed"] == 64
            assert results["shed"] > 0, stats
            assert results["ok"] > 0, stats
            assert stats["rejected"] == results["shed"]
            assert stats["pending"] <= stats["max_pending"] == 8
            # p50/p99 latency live in the shared obs registry
            series = registry().snapshot()[
                'lakesoul_ann_request_seconds{endpoint="default"}'
            ]
            assert series["count"] - before == results["ok"]
            p50 = _histogram_percentile(series, 0.5)
            p99 = _histogram_percentile(series, 0.99)
            assert 0 < p50 <= p99 < float("inf")
        finally:
            ep.close()

    def test_do_get_stream_keeps_admission_slot_until_delivery_done(
        self, tmp_path
    ):
        # the JSON scan path returns a LAZY GeneratorStream: the expensive
        # decode/merge work runs during delivery, after do_get returns — so
        # the admission slot must ride along with the stream, not be
        # released at handler exit (or N streams would decode concurrently
        # past any max_inflight)
        import gc
        import json as _json

        import pyarrow.flight as flight

        from lakesoul_tpu.service.flight import LakeSoulFlightServer

        catalog = LakeSoulCatalog(
            str(tmp_path / "wh"), db_path=str(tmp_path / "meta.db")
        )
        t = catalog.create_table("t", SCHEMA)
        t.write_arrow(
            pa.table({"id": np.arange(64), "v": np.zeros(64)}, schema=SCHEMA)
        )
        server = LakeSoulFlightServer(
            catalog, "grpc://127.0.0.1:0", max_inflight=1, max_queue=0
        )

        class _Ctx:
            def get_middleware(self, name):
                return None

        ticket = flight.Ticket(_json.dumps({"table": "t"}).encode())
        try:
            stream = server.do_get(_Ctx(), ticket)
            assert stream is not None
            # handler returned but delivery has not run: slot still held
            assert server.admission.snapshot()["inflight"] == 1
            with pytest.raises(flight.FlightUnavailableError):
                server.do_get(_Ctx(), ticket)
            # client disconnect before/while streaming: dropping the stream
            # must free the slot (generator finally, or the GC backstop for
            # a never-started generator)
            del stream
            gc.collect()
            assert server.admission.snapshot()["inflight"] == 0
        finally:
            server.shutdown()

    def test_flight_gateway_maps_overload_to_unavailable(self, tmp_path):
        import pyarrow.flight as flight

        from lakesoul_tpu.service.flight import (
            LakeSoulFlightClient,
            LakeSoulFlightServer,
        )

        catalog = LakeSoulCatalog(
            str(tmp_path / "wh"), db_path=str(tmp_path / "meta.db")
        )
        catalog.create_table("t", SCHEMA)
        server = LakeSoulFlightServer(
            catalog, "grpc://127.0.0.1:0", max_inflight=1, max_queue=0
        )
        try:
            client = LakeSoulFlightClient(f"grpc://127.0.0.1:{server.port}")
            # saturate the single slot → the wire answer is UNAVAILABLE
            server.admission.acquire()
            with pytest.raises(flight.FlightUnavailableError):
                client.action("metrics")
            server.admission.release()
            # slot free again: the same call succeeds
            assert client.action("metrics")
        finally:
            server.shutdown()
