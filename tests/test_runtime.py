"""Runtime subsystem: pool, staged pipelines, fault injection.

Covers the pipeline contract the data path now stands on — deterministic
ordered merge, bounded backpressure, cancellation/deadlines, exception
propagation (with the owning trace id in the failure log), and
LAKESOUL_FAULTS fault injection — plus the integration points: a killed
mid-pipeline scan stage surfaces to the caller, and the loader survives on
runtime pipelines with its stats contract intact.
"""

from __future__ import annotations

import logging
import threading
import time

import numpy as np
import pyarrow as pa
import pytest

from lakesoul_tpu import LakeSoulCatalog
from lakesoul_tpu.runtime import (
    DeadlineExceeded,
    FaultInjected,
    default_pool_size,
    get_pool,
    pipeline,
)
from lakesoul_tpu.runtime import faults


@pytest.fixture(autouse=True)
def _clean_faults():
    faults.clear()
    yield
    faults.clear()


# --------------------------------------------------------------------- pool
class TestWorkerPool:
    def test_singleton_and_sizing(self):
        p = get_pool()
        assert p is get_pool()
        assert p.size == default_pool_size() >= 2

    def test_in_worker_flag(self):
        p = get_pool()
        assert not p.in_worker()
        assert p.submit(p.in_worker).result() is True
        assert not p.in_worker()

    def test_env_sizing(self, monkeypatch):
        monkeypatch.setenv("LAKESOUL_RUNTIME_THREADS", "3")
        assert default_pool_size() == 3
        monkeypatch.setenv("LAKESOUL_RUNTIME_THREADS", "not-a-number")
        assert default_pool_size() >= 2


# ---------------------------------------------------------------- pipelines
class TestOrderedMerge:
    def test_map_parallel_preserves_order_despite_random_latency(self):
        rng = np.random.default_rng(0)
        delays = rng.uniform(0, 0.01, size=200).tolist()

        def work(i):
            time.sleep(delays[i])
            return i * 3

        out = list(
            pipeline("t").source(range(200)).map_parallel(work, workers=8).run()
        )
        assert out == [i * 3 for i in range(200)]

    def test_flat_map_parallel_preserves_order_and_flattens(self):
        def explode(i):
            time.sleep(0.001 * (i % 5))
            yield from (i, i + 1000)

        out = list(
            pipeline("t").source(range(50)).flat_map_parallel(explode, workers=4).run()
        )
        assert out == [v for i in range(50) for v in (i, i + 1000)]

    def test_pipelined_equals_serial_byte_for_byte(self):
        """The determinism contract on real work: same outputs whether the
        stage runs inline (pool of one) or fanned out."""

        def square(x):
            return x * x

        serial = [square(x) for x in range(100)]
        for workers in (1, 2, 7):
            got = list(
                pipeline("t").source(range(100)).map_parallel(square, workers=workers).run()
            )
            assert got == serial

    def test_stages_compose(self):
        out = list(
            pipeline("t")
            .source(range(20))
            .map(lambda x: x + 1, name="inc")
            .map_parallel(lambda x: x * 2, workers=3, name="dbl")
            .prefetch(4)
            .run()
        )
        assert out == [(x + 1) * 2 for x in range(20)]


class TestBackpressure:
    def test_map_parallel_inflight_bound(self):
        produced = []
        lock = threading.Lock()

        def source():
            for i in range(100):
                with lock:
                    produced.append(i)
                yield i

        it = pipeline("t").source(source()).map_parallel(
            lambda x: x, workers=2
        ).run()
        consumed = 0
        for _ in it:
            consumed += 1
            if consumed == 5:
                break
        # in-flight window is workers+1 (+1 being handed to the consumer):
        # an unbounded producer would have drained all 100 source items
        with lock:
            pulled = len(produced)
        assert pulled <= 5 + 2 + 1 + 1, pulled
        it.close()

    def test_prefetch_queue_bound(self):
        produced = []

        def source():
            for i in range(1000):
                produced.append(i)
                yield i

        it = pipeline("t").source(source()).prefetch(3).run()
        next(it)
        time.sleep(0.3)  # give the pump every chance to overrun
        assert len(produced) <= 3 + 2, len(produced)
        it.close()

    def test_flat_map_slot_buffer_bound(self):
        emitted = []

        def explode(i):
            for j in range(100):
                emitted.append((i, j))
                yield (i, j)

        it = pipeline("t").source(range(2)).flat_map_parallel(
            explode, workers=1, buffer=4
        ).run()
        next(it)
        time.sleep(0.3)
        # 2 active slots × (buffer + 1 in flight) + the consumed item
        assert len(emitted) <= 2 * 5 + 1, len(emitted)
        it.close()


class TestCancellationAndDeadline:
    def test_close_stops_producers(self):
        ran = []

        def slow(x):
            ran.append(x)
            time.sleep(0.005)
            return x

        it = pipeline("t").source(range(10_000)).map_parallel(slow, workers=2).run()
        next(it)
        it.close()
        time.sleep(0.2)
        settled = len(ran)
        time.sleep(0.2)
        assert len(ran) == settled  # nothing keeps running after close
        assert settled < 100

    def test_abandoned_loader_style_break(self):
        seen = 0
        it = pipeline("t").source(range(10_000)).map(lambda x: x).prefetch(2).run()
        for _ in it:
            seen += 1
            if seen >= 3:
                break
        it.close()
        assert seen == 3

    def test_deadline_exceeded_raises(self):
        it = pipeline("t", deadline_s=0.15).source(range(100)).map_parallel(
            lambda x: time.sleep(0.1) or x, workers=1
        ).run()
        with pytest.raises(DeadlineExceeded):
            list(it)

    def test_deadline_bounds_serial_map_stages_too(self):
        """deadline_s bounds the WHOLE run — including serial map stages
        that never touch a queue or future wait."""
        it = pipeline("t", deadline_s=0.15).source(range(100)).map(
            lambda x: time.sleep(0.05) or x
        ).run()
        start = time.perf_counter()
        with pytest.raises(DeadlineExceeded):
            list(it)
        assert time.perf_counter() - start < 2.0

    def test_deadline_not_hit_when_fast(self):
        out = list(
            pipeline("t", deadline_s=30.0).source(range(10)).map_parallel(
                lambda x: x, workers=2
            ).run()
        )
        assert out == list(range(10))


class TestExceptionPropagation:
    def test_map_parallel_error_reaches_consumer(self):
        def boom(x):
            if x == 7:
                raise ValueError("x was seven")
            return x

        with pytest.raises(ValueError, match="x was seven"):
            list(pipeline("t").source(range(20)).map_parallel(boom, workers=3).run())

    def test_flat_map_error_reaches_consumer_in_order(self):
        def explode(i):
            yield i
            if i == 2:
                raise RuntimeError("stream died")

        got = []
        with pytest.raises(RuntimeError, match="stream died"):
            for v in pipeline("t").source(range(10)).flat_map_parallel(
                explode, workers=2
            ).run():
                got.append(v)
        assert got == [0, 1, 2]  # everything before the failure, in order

    def test_source_error_through_prefetch(self):
        def source():
            yield 1
            raise OSError("decode failed")

        it = pipeline("t").source(source()).prefetch(2).run()
        assert next(it) == 1
        with pytest.raises(OSError, match="decode failed"):
            next(it)

    def test_map_stage_error_upstream_of_prefetch_surfaces_original(self):
        """A stage failure INSIDE the pump must reach the consumer as the
        original exception, never as an opaque PipelineCancelled — even
        though the cancel flag races the queue hand-off."""

        def boom(x):
            if x == 3:
                raise KeyError("collate died")
            return x

        for _ in range(20):  # the original bug was a race: hammer it
            with pytest.raises(KeyError, match="collate died"):
                list(
                    pipeline("t").source(range(10)).map(boom).prefetch(2).run()
                )

    def test_failure_log_carries_trace_id(self, caplog):
        from lakesoul_tpu.obs import span

        with caplog.at_level(logging.ERROR, logger="lakesoul_tpu.runtime.pipeline"):
            with span("test.op", trace_id="trace-pipeline-test"):
                with pytest.raises(ValueError):
                    list(
                        pipeline("t").source(range(5)).map_parallel(
                            lambda x: (_ for _ in ()).throw(ValueError("dead")),
                            workers=2,
                        ).run()
                    )
        assert any("trace-pipeline-test" in r.message for r in caplog.records)


# ----------------------------------------------------------- fault injection
class TestFaultInjection:
    def test_spec_parsing(self):
        s = faults.FaultSpec.parse("decode:0.5")
        assert (s.stage, s.probability, s.kind) == ("decode", 0.5, "error")
        s = faults.FaultSpec.parse("scan.fetch:1:delay:0.25")
        assert (s.stage, s.kind, s.seconds) == ("scan.fetch", "delay", 0.25)
        with pytest.raises(ValueError):
            faults.FaultSpec.parse("nocolon")
        with pytest.raises(ValueError):
            faults.FaultSpec.parse("s:2.0")  # probability out of range

    def test_error_injection_kills_stage(self):
        faults.install("victim:1.0")
        with pytest.raises(FaultInjected, match="victim"):
            list(
                pipeline("p").source(range(5)).map_parallel(
                    lambda x: x, workers=2, name="victim"
                ).run()
            )

    def test_qualified_stage_match(self):
        faults.install("only.this:1.0")
        # same stage name under a different pipeline: untouched
        out = list(
            pipeline("other").source(range(3)).map(lambda x: x, name="this").run()
        )
        assert out == [0, 1, 2]
        with pytest.raises(FaultInjected):
            list(pipeline("only").source(range(3)).map(lambda x: x, name="this").run())

    def test_delay_injection_slows_stage(self):
        faults.install("lag:1.0:delay:0.05")
        start = time.perf_counter()
        list(pipeline("p").source(range(3)).map(lambda x: x, name="lag").run())
        assert time.perf_counter() - start >= 0.14

    def test_env_spec_load(self, monkeypatch):
        monkeypatch.setattr(faults, "_ENV_LOADED", False)
        monkeypatch.setattr(faults, "_SPECS", [])
        monkeypatch.setattr(faults, "_ENABLED", False)
        monkeypatch.setenv("LAKESOUL_FAULTS", "a:0.5,b:1:delay:0.2")
        active = faults.active()
        assert [(s.stage, s.kind) for s in active] == [("a", "error"), ("b", "delay")]


# ------------------------------------------------------- scan-path integration
SCHEMA = pa.schema([("id", pa.int64()), ("v", pa.float64())])


def _two_file_table(tmp_path):
    catalog = LakeSoulCatalog(str(tmp_path / "wh"))
    t = catalog.create_table("ft", SCHEMA)
    t.write_arrow(pa.table({"id": np.arange(50), "v": np.zeros(50)}))
    t.write_arrow(pa.table({"id": np.arange(50, 100), "v": np.ones(50)}))
    return t


class TestScanFaults:
    def test_killed_decode_stage_propagates_with_trace_id(self, tmp_path, caplog):
        """Acceptance: kill a mid-pipeline stage during a real scan; the
        error reaches the caller AND the failure log carries the scan's
        trace id."""
        from lakesoul_tpu.obs import span

        t = _two_file_table(tmp_path)
        faults.install("scan_unit.decode:1.0")
        with caplog.at_level(logging.ERROR, logger="lakesoul_tpu.runtime.pipeline"):
            with span("test.scan", trace_id="trace-scan-kill"):
                with pytest.raises(FaultInjected):
                    t.scan().to_arrow()
        assert any("trace-scan-kill" in r.message for r in caplog.records)

    def test_scan_survives_injected_latency(self, tmp_path):
        t = _two_file_table(tmp_path)
        faults.install("scan_unit.decode:1.0:delay:0.02")
        table = t.scan().to_arrow()
        assert table.num_rows == 100
        assert sorted(table.column("id").to_pylist()) == list(range(100))


class TestScanDeterminism:
    def test_parallel_to_arrow_matches_serial(self, tmp_path):
        catalog = LakeSoulCatalog(str(tmp_path / "wh"))
        t = catalog.create_table("d", SCHEMA, primary_keys=["id"], hash_bucket_num=4)
        rng = np.random.default_rng(1)
        for _ in range(3):
            ids = rng.choice(10_000, 2_000, replace=False)
            t.write_arrow(pa.table({"id": np.sort(ids), "v": rng.normal(size=2_000)}))
        serial = t.scan().to_arrow(parallel=False)
        par = t.scan().to_arrow(parallel=True)
        assert serial.equals(par)

    def test_threaded_batches_match_serial_order(self, tmp_path):
        t = _two_file_table(tmp_path)
        serial = list(t.scan().batch_size(16).to_batches())
        threaded = list(t.scan().batch_size(16).to_batches(num_threads=4))
        assert len(serial) == len(threaded)
        for a, b in zip(serial, threaded):
            assert a.equals(b)

    def test_threaded_batches_multi_unit_flat_map_path(self, tmp_path):
        """Multi-unit scans take the runtime flat_map slot path (single-unit
        ones stay serial): the batch stream must still be byte-identical."""
        catalog = LakeSoulCatalog(str(tmp_path / "wh"))
        t = catalog.create_table("mu", SCHEMA, primary_keys=["id"], hash_bucket_num=4)
        rng = np.random.default_rng(3)
        for _ in range(2):
            ids = np.sort(rng.choice(50_000, 5_000, replace=False))
            t.write_arrow(pa.table({"id": ids, "v": rng.normal(size=5_000)}))
        assert len(t.scan().scan_plan()) > 1  # really exercises flat_map
        serial = list(t.scan().batch_size(512).to_batches())
        threaded = list(t.scan().batch_size(512).to_batches(num_threads=4))
        assert len(serial) == len(threaded)
        for a, b in zip(serial, threaded):
            assert a.equals(b)


class TestLoaderOnRuntime:
    def test_stats_report_queue_depth_and_stall(self, tmp_path):
        t = _two_file_table(tmp_path)
        it = t.scan().batch_size(32).to_jax_iter(device_put=False, drop_remainder=False)
        rows = 0
        for batch in it:
            rows += len(batch["id"])
        s = it.stats()
        assert rows == 100
        assert s["rows"] == 100 and s["epochs"] == 1
        assert s["stall_s"] >= 0.0 and "queue_depth" in s
        assert s["rows_per_sec"] > 0

    def test_loader_break_stops_pipeline(self, tmp_path):
        t = _two_file_table(tmp_path)
        it = t.scan().batch_size(8).to_jax_iter(device_put=False)
        n = 0
        for _ in it:
            n += 1
            if n == 2:
                break
        s = it.stats()
        assert s["batches"] == 2 and s["epochs"] == 0  # incomplete epoch

    def test_loader_fault_injection_surfaces(self, tmp_path):
        t = _two_file_table(tmp_path)
        faults.install("loader.collate:1.0")
        with pytest.raises(FaultInjected):
            for _ in t.scan().batch_size(32).to_jax_iter(device_put=False):
                pass


@pytest.mark.slow
class TestStress:
    def test_many_items_random_latency_ordered(self):
        rng = np.random.default_rng(7)
        delays = rng.uniform(0, 0.002, size=5000)

        def work(i):
            time.sleep(delays[i])
            return i

        out = list(
            pipeline("stress")
            .source(range(5000))
            .map_parallel(work, workers=8, name="jitter")
            .prefetch(16)
            .run()
        )
        assert out == list(range(5000))

    def test_stress_with_random_delay_faults(self):
        faults.install("stress2.jitter:0.05:delay:0.002")
        out = list(
            pipeline("stress2")
            .source(range(2000))
            .flat_map_parallel(lambda i: iter((i, -i)), workers=6, name="jitter")
            .run()
        )
        assert out == [v for i in range(2000) for v in (i, -i)]
