"""Scan-path efficiency contracts (PR 8):

- the zero-copy rebatch/collate is BYTE-IDENTICAL to the old
  concat_tables + combine_chunks implementation (kept verbatim here as the
  reference) across chunked / sliced / null-bearing / fixed-size-list /
  string / bool inputs;
- the opt-in collate buffer ring (``LAKESOUL_COLLATE_REUSE=1``) recycles
  buffers without changing delivered values;
- a no-PK (and a compacted-PK) scan DEGENERATES to raw decode: the merge
  and fill stages report ~0 in the ``lakesoul_scan_stage_seconds``
  breakdown while decode carries the leg;
- the stage breakdown itself populates for a real MOR scan.
"""

from __future__ import annotations

import numpy as np
import pyarrow as pa
import pytest

from lakesoul_tpu.data.jax_iter import _Rebatcher, _Window, _default_collate
from lakesoul_tpu.obs import stage_counts, stage_seconds


# --------------------------------------------------------------------------
# reference implementation: the pre-PR-8 rebatcher + collate, verbatim
# --------------------------------------------------------------------------


class _OldRebatcher:
    def __init__(self, batch_size: int):
        self.batch_size = batch_size
        self._pending: list[pa.Table] = []
        self._rows = 0

    def push(self, batch):
        t = pa.table(batch) if isinstance(batch, pa.RecordBatch) else batch
        self._pending.append(t)
        self._rows += len(t)
        while self._rows >= self.batch_size:
            yield self._pop(self.batch_size)

    def _pop(self, n: int) -> pa.Table:
        big = pa.concat_tables(self._pending)
        out = big.slice(0, n)
        rest = big.slice(n)
        self._pending = [rest] if len(rest) else []
        self._rows = len(rest)
        return out

    def tail(self):
        if self._rows == 0:
            return None
        out = pa.concat_tables(self._pending)
        self._pending, self._rows = [], 0
        return out


def _old_windows(batches, batch_size, drop_remainder):
    rb = _OldRebatcher(batch_size)
    for b in batches:
        yield from rb.push(b)
    if not drop_remainder:
        t = rb.tail()
        if t is not None:
            yield t


def _new_windows(batches, batch_size, drop_remainder):
    rb = _Rebatcher(batch_size)
    for b in batches:
        yield from rb.push(b)
    if not drop_remainder:
        w = rb.tail()
        if w is not None:
            yield w


def _new_collate(window: _Window):
    if window.fast:
        return window.collate(None)
    return _default_collate(window.to_table())


def _assert_same_pytree(got: dict, ref: dict):
    assert set(got) == set(ref)
    for name in ref:
        g, r = got[name], ref[name]
        assert g.dtype == r.dtype, (name, g.dtype, r.dtype)
        assert g.shape == r.shape, (name, g.shape, r.shape)
        if g.dtype == object:
            assert list(g) == list(r), name
        else:
            np.testing.assert_array_equal(g, r, err_msg=name)


def _roundtrip(batches, batch_size, drop_remainder=False):
    ref = [
        _default_collate(w)
        for w in _old_windows(batches, batch_size, drop_remainder)
    ]
    got = [
        _new_collate(w)
        for w in _new_windows(batches, batch_size, drop_remainder)
    ]
    assert len(got) == len(ref), (len(got), len(ref))
    for g, r in zip(got, ref):
        _assert_same_pytree(g, r)
    return got


# --------------------------------------------------------------------------
# byte identity across input shapes
# --------------------------------------------------------------------------


def _numeric_batches(n_batches=7, rows=300, seed=0):
    rng = np.random.default_rng(seed)
    out = []
    for i in range(n_batches):
        n = rows + (i * 37) % 100
        out.append(pa.record_batch({
            "id": pa.array(np.arange(i * 1000, i * 1000 + n, dtype=np.int64)),
            "f32": pa.array(rng.normal(size=n).astype(np.float32)),
            "f64": pa.array(rng.normal(size=n)),
            "i32": pa.array(rng.integers(-50, 50, n).astype(np.int32)),
        }))
    return out


class TestByteIdentity:
    def test_numeric_fast_path_matches_old(self):
        batches = _numeric_batches()
        got = _roundtrip(batches, 256)
        # sanity: these windows take the fused path
        ws = list(_new_windows(_numeric_batches(), 256, False))
        assert all(w.fast for w in ws)
        assert got, "no windows emitted"

    def test_window_not_aligned_to_batches(self):
        # window size coprime to batch lengths: every window spans parts
        _roundtrip(_numeric_batches(), 211)
        _roundtrip(_numeric_batches(), 997)

    def test_chunked_table_input(self):
        t = pa.Table.from_batches(_numeric_batches(4))
        assert t.column("id").num_chunks > 1
        _roundtrip([t], 123)

    def test_sliced_batches_nonzero_offset(self):
        sliced = [b.slice(17, len(b) - 40) for b in _numeric_batches()]
        assert all(len(b) for b in sliced)
        _roundtrip(sliced, 201)

    def test_null_bearing_columns_fall_back_identically(self):
        rng = np.random.default_rng(1)
        batches = []
        for i in range(5):
            n = 200
            vals = rng.normal(size=n)
            mask = rng.random(n) < 0.2
            batches.append(pa.record_batch({
                "id": pa.array(np.arange(n, dtype=np.int64)),
                "v": pa.array([None if m else float(x) for m, x in zip(mask, vals)],
                              type=pa.float64()),
            }))
        ws = list(_new_windows(batches, 128, False))
        assert not all(w.fast for w in ws)  # nulls force the fallback
        _roundtrip(batches, 128)

    def test_fixed_size_list_tensor_columns(self):
        rng = np.random.default_rng(2)
        batches = []
        for i in range(4):
            n = 150 + i
            batches.append(pa.record_batch({
                "id": pa.array(np.arange(n, dtype=np.int64)),
                "emb": pa.FixedSizeListArray.from_arrays(
                    rng.normal(size=n * 8).astype(np.float32), 8
                ),
            }))
        got = _roundtrip(batches, 97)
        assert got[0]["emb"].shape[1] == 8

    def test_sliced_fixed_size_list(self):
        rng = np.random.default_rng(3)
        n = 400
        b = pa.record_batch({
            "emb": pa.FixedSizeListArray.from_arrays(
                rng.normal(size=n * 4).astype(np.float32), 4
            ),
            "id": pa.array(np.arange(n, dtype=np.int64)),
        })
        _roundtrip([b.slice(33, 300), b.slice(5, 111)], 64)

    def test_strings_and_bools_fall_back_identically(self):
        batches = []
        for i in range(3):
            n = 120
            batches.append(pa.record_batch({
                "id": pa.array(np.arange(n, dtype=np.int64)),
                "name": pa.array([f"r{i}_{j}" for j in range(n)]),
                "flag": pa.array([j % 3 == 0 for j in range(n)]),
            }))
        out = _roundtrip(batches, 77)
        assert out[0]["name"].dtype == object
        assert out[0]["flag"].dtype == np.bool_

    def test_timestamp_columns_fast_path(self):
        batches = []
        for i in range(3):
            n = 90
            batches.append(pa.record_batch({
                "ts": pa.array(
                    (np.arange(n) + i * 1000).astype("datetime64[us]")
                ),
                "id": pa.array(np.arange(n, dtype=np.int64)),
            }))
        ws = list(_new_windows(batches, 50, False))
        assert all(w.fast for w in ws)
        _roundtrip(batches, 50)

    def test_drop_remainder_boundary(self):
        batches = _numeric_batches(3, rows=100)
        _roundtrip(batches, 100, drop_remainder=True)
        _roundtrip(batches, 10_000, drop_remainder=False)  # single tail window


class TestBufferRing:
    def test_ring_recycles_without_value_change(self, tmp_warehouse, monkeypatch):
        from lakesoul_tpu import LakeSoulCatalog

        catalog = LakeSoulCatalog(str(tmp_warehouse))
        schema = pa.schema([("id", pa.int64()), ("v", pa.float64())])
        t = catalog.create_table("ring", schema)
        rng = np.random.default_rng(0)
        t.write_arrow(pa.table({
            "id": np.arange(5000, dtype=np.int64),
            "v": rng.normal(size=5000),
        }, schema=schema))

        def snap(it):
            # copy out immediately — the ring's documented consumer contract
            return [{k: np.copy(v) for k, v in b.items()} for b in it]

        plain = snap(t.scan().batch_size(512).to_jax_iter(
            device_put=False, drop_remainder=False
        ))
        monkeypatch.setenv("LAKESOUL_COLLATE_REUSE", "1")
        it = t.scan().batch_size(512).to_jax_iter(
            device_put=False, drop_remainder=False
        )
        assert it._ring is not None
        reused = snap(it)
        assert len(plain) == len(reused)
        for a, b in zip(plain, reused):
            _assert_same_pytree(b, a)

    def test_ring_slots_rotate(self):
        from lakesoul_tpu.data.jax_iter import _BufferRing

        ring = _BufferRing(3)
        s = [ring.next_slot() for _ in range(6)]
        assert s[0] is s[3] and s[1] is s[4] and s[2] is s[5]
        assert s[0] is not s[1]


# --------------------------------------------------------------------------
# degeneracy: no-PK / compacted scans are raw-decode plans
# --------------------------------------------------------------------------


def _stage_delta(before_s, before_c):
    after_s, after_c = stage_seconds(), stage_counts()
    return (
        {k: after_s[k] - before_s[k] for k in after_s},
        {k: after_c[k] - before_c[k] for k in after_c},
    )


class TestDegeneracy:
    def _build(self, tmp_warehouse, name, *, primary_keys=None, rows=200_000,
               budget=None):
        from lakesoul_tpu import LakeSoulCatalog

        catalog = LakeSoulCatalog(str(tmp_warehouse))
        props = {}
        if budget:
            props["lakesoul.memory_budget_bytes"] = str(budget)
        schema = pa.schema([
            ("id", pa.int64()), ("v", pa.float64()), ("f0", pa.float32()),
        ])
        t = catalog.create_table(
            name, schema, primary_keys=primary_keys or [],
            hash_bucket_num=1, properties=props,
        )
        rng = np.random.default_rng(0)
        per = rows // 4
        for i in range(4):
            ids = np.arange(i * per, (i + 1) * per, dtype=np.int64)
            t.write_arrow(pa.table({
                "id": ids,
                "v": rng.normal(size=per),
                "f0": rng.normal(size=per).astype(np.float32),
            }, schema=schema))
        return t

    def _scan_all(self, t):
        rows = 0
        for b in t.scan().batch_size(8192).to_batches():
            rows += len(b)
        return rows

    def test_no_pk_stream_merge_fill_near_zero(self, tmp_warehouse):
        # a small budget forces the bounded STREAMING branch
        t = self._build(tmp_warehouse, "nopk", budget=1 << 20)
        before = stage_seconds(), stage_counts()
        rows = self._scan_all(t)
        ds, dc = _stage_delta(*before)
        assert rows == 200_000
        assert dc["merge"] == 0, dc
        assert ds["decode"] > 0, ds
        # fill may be touched by identity-exit probes; it must stay noise
        assert ds["merge"] + ds["fill"] <= max(0.10 * ds["decode"], 0.005), ds

    def test_no_pk_materialize_merge_fill_near_zero(self, tmp_warehouse):
        t = self._build(tmp_warehouse, "nopk_mat")  # default budget: hybrid materialize
        before = stage_seconds(), stage_counts()
        rows = self._scan_all(t)
        ds, dc = _stage_delta(*before)
        assert rows == 200_000
        assert dc["merge"] == 0, dc
        assert ds["merge"] + ds["fill"] <= max(0.10 * ds["decode"], 0.005), ds

    def test_compacted_pk_scan_merge_near_decode_zero(self, tmp_warehouse):
        t = self._build(tmp_warehouse, "pk", primary_keys=["id"])
        t.compact()
        before = stage_seconds(), stage_counts()
        rows = self._scan_all(t)
        ds, dc = _stage_delta(*before)
        assert rows == 200_000
        # a compacted PK unit still passes through the merge entry point,
        # but the strictly-increasing fast exit reduces it to one O(n)
        # compare — a small fraction of decode
        assert ds["merge"] + ds["fill"] <= max(0.25 * ds["decode"], 0.01), ds

    def test_mor_scan_populates_breakdown(self, tmp_warehouse):
        t = self._build(tmp_warehouse, "mor", primary_keys=["id"])
        # overlapping upsert wave → real merge work
        rng = np.random.default_rng(1)
        ids = rng.choice(200_000, 50_000, replace=False).astype(np.int64)
        t.upsert(pa.table({
            "id": ids,
            "v": rng.normal(size=len(ids)),
            "f0": rng.normal(size=len(ids)).astype(np.float32),
        }))
        before = stage_seconds(), stage_counts()
        batches = list(t.scan().batch_size(4096).to_jax_iter(
            device_put=False, drop_remainder=False
        ))
        ds, dc = _stage_delta(*before)
        rows = sum(len(b["id"]) for b in batches)
        assert rows == 200_000  # upsert overwrote, no new keys
        for stage in ("decode", "merge", "rebatch", "collate", "queue"):
            assert dc[stage] > 0, (stage, dc)
        assert ds["decode"] > 0 and ds["merge"] > 0, ds
