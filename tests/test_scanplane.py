"""Disaggregated scan plane (PR 11 tentpole).

The acceptance contract, proven here at tier-1 speed with in-process
workers and real Flight exchanges (the subprocess SIGKILL chaos lives in
test_scanplane_chaos.py under the ``slow`` marker, with a quick smoke
variant at the bottom of this file):

- session plans are pinned, deterministic, and shared (same request+table
  state → same session id; ranges shard exactly like ``scan.shard``);
- worker-produced spool segments are byte-identical to the in-process
  scan — for every client rank, over both delivery modes (shared-memory
  fast path and socket);
- the DoExchange verb is JWT/RBAC-gated and admission-bounded exactly
  like do_get (typed UNAVAILABLE sheds under 64 concurrent exchanges);
- a client mid-stream survives its worker dying: the stream stalls until
  a peer produces the range, then completes with no duplicate and no
  missing batch; explicit resume (start_range/start_batch) redelivers
  from exactly the recorded position;
- the batch-source seam makes the plane a drop-in source for
  to_jax_iter / torch / ray — stats, queue-depth and stage attribution
  intact, with the workers' producer stages merged into the client's
  registry tagged ``worker=``.
"""

from __future__ import annotations

import json
import os
import pathlib
import subprocess
import sys
import threading
import time

import numpy as np
import pyarrow as pa
import pyarrow.flight as flight
import pytest

from lakesoul_tpu import LakeSoulCatalog
from lakesoul_tpu.errors import ConfigError
from lakesoul_tpu.obs import queue_seconds_by_consumer, registry
from lakesoul_tpu.scanplane.client import ScanPlaneClient
from lakesoul_tpu.scanplane.delivery import ScanPlaneDelivery
from lakesoul_tpu.scanplane.session import ScanSession
from lakesoul_tpu.scanplane.worker import ScanPlaneWorker
from lakesoul_tpu.scanplane import spool as spool_mod
from lakesoul_tpu.service.flight import LakeSoulFlightServer

REPO = str(pathlib.Path(__file__).resolve().parent.parent)
SCHEMA = pa.schema([("id", pa.int64()), ("v", pa.float64()), ("f", pa.float32())])


def _make_table(tmp_path, *, rows=24_000, commits=3, pk=True, name="t"):
    catalog = LakeSoulCatalog(
        str(tmp_path / "wh"), db_path=str(tmp_path / "meta.db")
    )
    t = catalog.create_table(
        name, SCHEMA,
        primary_keys=["id"] if pk else None,
        hash_bucket_num=2 if pk else None,
    )
    rng = np.random.default_rng(7)
    per = rows // commits
    for _ in range(commits):
        ids = np.sort(rng.choice(rows * 2, per, replace=False)).astype(np.int64)
        t.upsert(pa.table({
            "id": ids,
            "v": rng.normal(size=per),
            "f": rng.normal(size=per).astype(np.float32),
        }, schema=SCHEMA)) if pk else t.write_arrow(pa.table({
            "id": ids, "v": rng.normal(size=per),
            "f": rng.normal(size=per).astype(np.float32),
        }, schema=SCHEMA))
    return catalog, t


class _Plane:
    """In-process fleet: flight server (spool delivery) + worker thread."""

    def __init__(self, catalog, tmp_path, *, workers=1, wait_s=30.0,
                 lease_ttl_s=10.0, jwt_secret=None, start_workers=True,
                 max_inflight=None, max_queue=None):
        self.spool = str(tmp_path / "spool")
        os.makedirs(self.spool, exist_ok=True)
        self.catalog = catalog
        self.delivery = ScanPlaneDelivery(catalog, self.spool, wait_s=wait_s)
        self.server = LakeSoulFlightServer(
            catalog, "grpc://127.0.0.1:0", scanplane=self.delivery,
            jwt_secret=jwt_secret, max_inflight=max_inflight,
            max_queue=max_queue,
        )
        threading.Thread(target=self.server.serve, daemon=True).start()
        self.location = f"grpc://127.0.0.1:{self.server.port}"
        self._stops = []
        self.workers = [
            ScanPlaneWorker(
                catalog, self.spool, lease_ttl_s=lease_ttl_s,
                poll_interval_s=0.02, worker_id=f"w{i}",
            )
            for i in range(workers)
        ]
        if start_workers:
            for w in self.workers:
                self.start_worker(w)

    def start_worker(self, w):
        stop = threading.Event()
        self._stops.append(stop)
        threading.Thread(
            target=w.run_forever, kwargs={"stop_event": stop}, daemon=True
        ).start()
        return stop

    def close(self):
        for s in self._stops:
            s.set()
        self.server.shutdown()


# ---------------------------------------------------------------- sessions


class TestSession:
    def test_plan_is_pinned_and_shared(self, tmp_path):
        catalog, t = _make_table(tmp_path, rows=6000)
        req = {"table": "t", "batch_size": 2048}
        a = ScanSession.plan(catalog, req)
        b = ScanSession.plan(catalog, {"table": "t", "batch_size": 2048,
                                       "namespace": "default"})
        assert a.session_id == b.session_id  # canonicalized request
        assert len(a.ranges) == len(t.scan().scan_plan())
        # a commit changes the version digest → a NEW session
        t.upsert(pa.table({
            "id": np.arange(8, dtype=np.int64),
            "v": np.zeros(8), "f": np.zeros(8, dtype=np.float32),
        }, schema=SCHEMA))
        c = ScanSession.plan(catalog, req)
        assert c.session_id != a.session_id

    def test_manifest_round_trip(self, tmp_path):
        catalog, _ = _make_table(tmp_path, rows=4000)
        session = ScanSession.plan(catalog, {"table": "t"})
        sdir = session.publish(str(tmp_path / "spool"))
        assert os.path.isdir(sdir)
        loaded = ScanSession.load(str(tmp_path / "spool"), session.session_id)
        assert loaded.to_json() == session.to_json()
        assert [u.data_files for u in loaded.ranges] == [
            u.data_files for u in session.ranges
        ]

    def test_client_ranges_match_scan_shard(self, tmp_path):
        catalog, t = _make_table(tmp_path, rows=8000)
        session = ScanSession.plan(catalog, {"table": "t"})
        units = t.scan().scan_plan()
        for world in (2, 3):
            for rank in range(world):
                picked = [
                    tuple(session.ranges[i].data_files)
                    for i in session.client_ranges(rank, world)
                ]
                sharded = [
                    tuple(u.data_files)
                    for u in t.scan().shard(rank, world).scan_plan()
                ]
                assert picked == sharded, (rank, world)
        assert session.client_ranges(None, None) == list(range(len(units)))

    def test_unsessionable_scans_rejected(self, tmp_path):
        from lakesoul_tpu.scanplane.session import session_request_from_scan

        catalog, t = _make_table(tmp_path, rows=2000)
        with pytest.raises(ConfigError, match="snapshot"):
            session_request_from_scan(t.scan().snapshot_at(1))
        with pytest.raises(ConfigError, match="cache"):
            session_request_from_scan(t.scan().cache())

    def test_cdc_delete_flag_rides_the_session(self, tmp_path):
        """with_cdc_deletes() must survive the request round trip — a
        worker rebuilding the scan server-side would otherwise silently
        DROP the delete rows the caller asked to keep."""
        from lakesoul_tpu.scanplane.session import (
            canonical_request,
            scan_for_request,
            session_request_from_scan,
        )

        catalog, t = _make_table(tmp_path, rows=2000)
        req = session_request_from_scan(t.scan().with_cdc_deletes())
        assert req["keep_cdc_deletes"] is True
        rebuilt = scan_for_request(catalog, req)
        assert rebuilt._keep_cdc_deletes is True
        # the flag is part of the session key: keep vs drop are DIFFERENT
        # sessions (different delivered rows on CDC tables)
        assert canonical_request(req) != canonical_request(
            session_request_from_scan(t.scan())
        )


# ------------------------------------------------------------------- spool


class TestSpool:
    def test_round_trip_zero_copy_and_sidecar(self, tmp_path):
        sdir = str(tmp_path)
        t = pa.table({"x": np.arange(1000, dtype=np.int64)})
        batches = t.to_batches(max_chunksize=256)
        side = spool_mod.write_range(
            sdir, 3, t.schema, iter(batches), holder="w0",
            meta={"worker": "w0", "fence": 2},
        )
        assert side["rows"] == 1000 and side["batches"] == 4
        assert spool_mod.range_ready(sdir, 3)
        assert spool_mod.ready_ranges(sdir) == {3}
        schema, got = spool_mod.read_range(sdir, 3)
        assert schema == t.schema
        assert [b.num_rows for b in got] == [256, 256, 256, 232]
        assert pa.Table.from_batches(got).equals(t)
        # zero-copy: the numpy view aliases the mapping, no materialization
        arr = got[0].column(0).to_numpy(zero_copy_only=True)
        assert arr[5] == 5
        assert spool_mod.read_sidecar(sdir, 3)["fence"] == 2

    def test_tmp_debris_swept_publication_atomic(self, tmp_path):
        sdir = str(tmp_path)
        # a dead producer's half-written files
        open(os.path.join(sdir, "range-00001.arrow.tmp-dead"), "wb").write(b"x")
        open(os.path.join(sdir, "range-00001.json.tmp-dead"), "w").write("{}")
        assert not spool_mod.range_ready(sdir, 1)
        spool_mod.sweep_tmp_debris(sdir, 1)
        assert os.listdir(sdir) == []


# ------------------------------------------------------------------ worker


class TestWorker:
    def test_produces_byte_identical_ranges(self, tmp_path):
        catalog, t = _make_table(tmp_path)
        spool_dir = str(tmp_path / "spool")
        session = ScanSession.plan(catalog, {"table": "t", "batch_size": 4096})
        session.publish(spool_dir)
        worker = ScanPlaneWorker(catalog, spool_dir, lease_ttl_s=10)
        counts = worker.poll_once()
        assert counts["produced"] == len(session.ranges)
        assert counts["errors"] == 0
        # concatenated spool batches == the serial in-process stream
        got = []
        sdir = session.dir(spool_dir)
        for i in range(len(session.ranges)):
            _, batches = spool_mod.read_range(sdir, i)
            got.extend(batches)
        want = list(t.scan().batch_size(4096).to_batches())
        assert len(got) == len(want)
        for a, b in zip(got, want):
            assert a.equals(b)
        # sidecars carry producer attribution: stages + fencing token
        side = spool_mod.read_sidecar(sdir, 0)
        assert side["fence"] >= 1 and side["worker"] == worker.worker_id
        assert "decode" in side.get("stages", {})

    def test_live_peer_lease_respected_then_taken_over(self, tmp_path):
        catalog, _ = _make_table(tmp_path, rows=4000)
        spool_dir = str(tmp_path / "spool")
        session = ScanSession.plan(catalog, {"table": "t"})
        session.publish(spool_dir)
        store = catalog.client.store
        key = f"scanplane/{session.session_id}/0"
        # a live peer holds range 0 with a long TTL: respected
        assert store.acquire_lease(key, "peer", 60_000) is not None
        worker = ScanPlaneWorker(catalog, spool_dir, lease_ttl_s=5)
        counts = worker.poll_once()
        assert counts["lease_held"] == 1
        assert not spool_mod.range_ready(session.dir(spool_dir), 0)
        # the peer dies (lease expires): the worker takes over and produces
        expired = store.get_lease(key)
        assert store.renew_lease(key, "peer", expired.fencing_token, 1) is not None
        time.sleep(0.05)
        counts = worker.poll_once()
        assert counts["produced"] >= 1
        assert spool_mod.range_ready(session.dir(spool_dir), 0)
        # the takeover bumped the fencing token past the dead peer's
        assert spool_mod.read_sidecar(session.dir(spool_dir), 0)["fence"] == 2


# ---------------------------------------------------- exchange: inline mode


@pytest.fixture()
def inline_gateway(tmp_path):
    catalog, t = _make_table(tmp_path)
    server = LakeSoulFlightServer(catalog, "grpc://127.0.0.1:0")
    yield catalog, t, server, f"grpc://127.0.0.1:{server.port}"
    server.shutdown()


class TestExchangeInline:
    def test_byte_identity_and_shards(self, inline_gateway):
        _, t, _, loc = inline_gateway
        client = ScanPlaneClient(loc)
        local = list(t.scan().batch_size(4096).to_batches())
        remote = list(client.iter_batches({"table": "t", "batch_size": 4096}))
        assert len(remote) == len(local)
        for a, b in zip(remote, local):
            assert a.equals(b)
        for rank in range(3):
            want = list(t.scan().batch_size(4096).shard(rank, 3).to_batches())
            got = list(client.iter_batches(
                {"table": "t", "batch_size": 4096}, rank=rank, world=3
            ))
            assert len(got) == len(want)
            assert all(a.equals(b) for a, b in zip(got, want))

    def test_projection_and_filter_ride_the_session(self, inline_gateway):
        _, t, _, loc = inline_gateway
        client = ScanPlaneClient(loc)
        scan = t.scan().select(["id", "f"]).filter("id < 1000").batch_size(2048)
        want = list(scan.to_batches())
        got = list(client.iter_batches({
            "table": "t", "columns": ["id", "f"],
            "filter": scan._filter._to_dict(), "batch_size": 2048,
        }))
        assert sum(b.num_rows for b in got) == sum(b.num_rows for b in want)
        assert all(a.equals(b) for a, b in zip(got, want))
        assert got[0].schema.names == ["id", "f"]

    def test_unknown_verb_rejected(self, inline_gateway):
        *_, loc = inline_gateway
        fc = flight.FlightClient(loc)
        desc = flight.FlightDescriptor.for_command(
            json.dumps({"verb": "nope", "table": "t"}).encode()
        )
        writer, reader = fc.do_exchange(desc)
        with pytest.raises(flight.FlightServerError, match="unknown exchange verb"):
            with writer:
                reader.read_chunk()


# ------------------------------------------------------ exchange: auth/RBAC


class TestExchangeAuth:
    def _secured(self, tmp_path):
        catalog, t = _make_table(tmp_path, rows=4000)
        catalog.client.create_table(
            "priv", f"{tmp_path}/wh/default/priv", SCHEMA, domain="team1"
        )
        server = LakeSoulFlightServer(
            catalog, "grpc://127.0.0.1:0", jwt_secret="s3cr3t"
        )
        from lakesoul_tpu.service.jwt import Claims

        token = server.jwt_server.create_token(
            Claims(sub="alice", group="public")
        )
        return catalog, t, server, f"grpc://127.0.0.1:{server.port}", token

    def test_unauthenticated_exchange_rejected(self, tmp_path):
        *_, server, loc, _ = self._secured(tmp_path)
        try:
            client = ScanPlaneClient(loc, max_attempts=1)  # no auth header
            with pytest.raises(flight.FlightUnauthenticatedError):
                list(client.iter_batches({"table": "t"}))
        finally:
            server.shutdown()

    def test_rbac_denied_on_foreign_domain_table(self, tmp_path):
        *_, server, loc, token = self._secured(tmp_path)
        try:
            client = ScanPlaneClient(loc, token=token, max_attempts=1)
            with pytest.raises(flight.FlightUnauthorizedError):
                list(client.iter_batches({"table": "priv"}))
            # the same identity streams public tables fine
            rows = sum(
                b.num_rows for b in client.iter_batches({"table": "t"})
            )
            assert rows > 0
        finally:
            server.shutdown()

    def test_tampered_token_rejected(self, tmp_path):
        *_, server, loc, token = self._secured(tmp_path)
        try:
            bad = token[:-4] + ("AAAA" if token[-4:] != "AAAA" else "BBBB")
            client = ScanPlaneClient(loc, token=bad, max_attempts=1)
            with pytest.raises(flight.FlightUnauthenticatedError):
                list(client.iter_batches({"table": "t"}))
        finally:
            server.shutdown()


# ------------------------------------------- exchange: overload (64 clients)


class TestExchangeOverload:
    def test_64_concurrent_exchanges_typed_sheds_bounded_queue(self, tmp_path):
        """The new verb rides the SAME admission gate as do_get/do_put:
        beyond max_inflight + max_queue, exchanges shed with Flight
        UNAVAILABLE (typed, retryable) instead of stacking an unbounded
        backlog — the test_resilience overload pattern on DoExchange."""
        catalog, t = _make_table(tmp_path, rows=32_000)
        server = LakeSoulFlightServer(
            catalog, "grpc://127.0.0.1:0", max_inflight=2, max_queue=2,
        )
        loc = f"grpc://127.0.0.1:{server.port}"
        want_rows = t.scan().count_rows()
        results = {"ok": 0, "shed": 0}
        guard = threading.Lock()
        gate = threading.Event()

        def client_run():
            gate.wait()
            c = ScanPlaneClient(loc, max_attempts=1)  # no retry: count sheds
            try:
                rows = sum(
                    b.num_rows
                    for b in c.iter_batches({"table": "t", "batch_size": 2048})
                )
                assert rows == want_rows
                with guard:
                    results["ok"] += 1
            except flight.FlightUnavailableError:
                with guard:
                    results["shed"] += 1

        threads = [threading.Thread(target=client_run) for _ in range(64)]
        try:
            for th in threads:
                th.start()
            gate.set()
            for th in threads:
                th.join(120.0)
            assert results["ok"] + results["shed"] == 64
            assert results["ok"] > 0 and results["shed"] > 0, results
            snap = server.admission.snapshot()
            assert snap["inflight"] == 0 and snap["waiting"] == 0
        finally:
            server.shutdown()


# ------------------------------------- spool delivery, shm, death, resume


class TestSpoolDelivery:
    def test_shm_and_socket_paths_byte_identical(self, tmp_path):
        catalog, t = _make_table(tmp_path)
        plane = _Plane(catalog, tmp_path)
        try:
            local = list(t.scan().batch_size(4096).to_batches())
            before = registry().snapshot().get(
                'lakesoul_scanplane_client_ranges_total{mode="shm"}', 0
            )
            shm_client = ScanPlaneClient(plane.location, shm=True)
            got = list(shm_client.iter_batches({"table": "t", "batch_size": 4096}))
            assert len(got) == len(local)
            assert all(a.equals(b) for a, b in zip(got, local))
            after = registry().snapshot().get(
                'lakesoul_scanplane_client_ranges_total{mode="shm"}', 0
            )
            assert after > before  # the fast path actually engaged
            sock_client = ScanPlaneClient(plane.location, shm=False)
            got2 = list(sock_client.iter_batches({"table": "t", "batch_size": 4096}))
            assert all(a.equals(b) for a, b in zip(got2, local))
        finally:
            plane.close()

    def test_worker_stages_merged_into_client_registry(self, tmp_path):
        catalog, t = _make_table(tmp_path)
        plane = _Plane(catalog, tmp_path)
        try:
            client = ScanPlaneClient(plane.location)
            list(client.iter_batches({"table": "t", "batch_size": 8192}))
            tagged = [
                k for k in registry().snapshot()
                if k.startswith("lakesoul_scan_stage_seconds")
                and 'worker="w0"' in k
            ]
            assert any('stage="decode"' in k for k in tagged), tagged
        finally:
            plane.close()

    def test_client_survives_worker_death_mid_stream(self, tmp_path):
        """A client consuming while its worker dies: the stream stalls on
        the unproduced range until a peer produces it, then completes —
        no duplicate, no missing batches (the mid-stream recovery leg of
        the DoExchange coverage satellite)."""
        catalog, t = _make_table(tmp_path)
        plane = _Plane(catalog, tmp_path, workers=0, wait_s=60)
        try:
            session = plane.delivery.resolve_session(
                {"table": "t", "batch_size": 4096}
            )
            nranges = len(session.ranges)
            assert nranges >= 2
            store = catalog.client.store
            # the doomed worker "w-dead" produces ONLY range 0 (a live
            # lease from this test blocks the rest), then dies
            held = []
            for i in range(1, nranges):
                key = f"scanplane/{session.session_id}/{i}"
                held.append((key, store.acquire_lease(key, "blocker", 60_000)))
            w_dead = ScanPlaneWorker(
                catalog, plane.spool, worker_id="w-dead", lease_ttl_s=5
            )
            counts = w_dead.poll_once()
            assert counts["produced"] == 1 and counts["lease_held"] == nranges - 1

            got = []
            done = threading.Event()
            errors = []

            def consume():
                try:
                    c = ScanPlaneClient(plane.location)
                    for b in c.iter_batches({"table": "t", "batch_size": 4096}):
                        got.append(b)
                    done.set()
                except BaseException as e:  # surfaced below
                    errors.append(e)
                    done.set()

            threading.Thread(target=consume, daemon=True).start()
            # the stream delivers range 0 then stalls (worker dead, leases
            # still held by the "dead" holder)
            time.sleep(0.5)
            assert not done.is_set()
            assert len(got) >= 1
            # the dead holder's leases expire → a peer takes over
            for key, lease in held:
                store.release_lease(key, "blocker", lease.fencing_token)
            peer = ScanPlaneWorker(
                catalog, plane.spool, worker_id="w-peer", lease_ttl_s=5
            )
            peer.poll_once()
            assert done.wait(30.0), "client never completed after takeover"
            assert not errors, errors
            want = list(t.scan().batch_size(4096).to_batches())
            assert len(got) == len(want)
            assert all(a.equals(b) for a, b in zip(got, want))
        finally:
            plane.close()

    def test_reconnect_pin_survives_commits_and_fails_loudly_when_gone(
        self, tmp_path
    ):
        """Resume-by-position is only exactly-once against the SAME plan:
        a pinned session keeps serving its pinned ranges even after the
        table advances (the manifest is still spooled), and a pin that no
        longer resolves (pruned spool) fails the stream loudly instead of
        silently serving a different plan's rows."""
        import shutil

        from lakesoul_tpu.errors import LakeSoulError

        catalog, t = _make_table(tmp_path)
        plane = _Plane(catalog, tmp_path)
        try:
            req = {"table": "t", "batch_size": 4096}
            pinned = plane.delivery.resolve_session(req)
            # a commit lands mid-stream: unpinned requests mint a NEW
            # session, the pinned one still resolves to the OLD plan
            t.upsert(pa.table({
                "id": np.arange(4, dtype=np.int64),
                "v": np.zeros(4), "f": np.zeros(4, dtype=np.float32),
            }, schema=SCHEMA))
            fresh = plane.delivery.resolve_session(req)
            assert fresh.session_id != pinned.session_id
            again = plane.delivery.resolve_session(
                {**req, "session": pinned.session_id}
            )
            assert again.session_id == pinned.session_id
            assert again.version_digest == pinned.version_digest
            # the pinned spool vanishes (prune): the stream must die loud
            shutil.rmtree(pinned.dir(plane.spool))
            with pytest.raises(LakeSoulError, match="no longer exists"):
                plane.delivery.resolve_session(
                    {**req, "session": pinned.session_id}
                )
        finally:
            plane.close()

    def test_explicit_resume_positions_redeliver_exactly(self, tmp_path):
        catalog, t = _make_table(tmp_path)
        plane = _Plane(catalog, tmp_path)
        try:
            req = {"table": "t", "batch_size": 2048}
            for shm in (True, False):
                client = ScanPlaneClient(plane.location, shm=shm)
                full = list(client.iter_batches(req))
                # ranges have >1 batch each at this batch size; resume from
                # (range 1, batch 2) must equal the tail of the full stream
                session = plane.delivery.resolve_session(req)
                first_range_batches = spool_mod.read_sidecar(
                    session.dir(plane.spool),
                    session.client_ranges(None, None)[0],
                )["batches"]
                resumed = list(client.iter_batches(
                    req, start_range=1, start_batch=2
                ))
                want = full[first_range_batches + 2:]
                assert len(resumed) == len(want)
                assert all(a.equals(b) for a, b in zip(resumed, want)), shm
        finally:
            plane.close()


# --------------------------------------------------- seam: jax / torch / ray


class TestBatchSourceSeam:
    def test_jax_iter_drop_in_with_stats_and_attribution(self, tmp_path):
        catalog, t = _make_table(tmp_path)
        plane = _Plane(catalog, tmp_path)
        try:
            client = ScanPlaneClient(plane.location)
            scan = t.scan().batch_size(2048).via_scanplane(client)
            it = scan.to_jax_iter(
                device_put=False, drop_remainder=False, consumer="trainer-0"
            )
            remote_rows = sum(len(b["id"]) for b in it)
            assert remote_rows == t.scan().count_rows()
            stats = it.stats()
            assert stats["rows"] == remote_rows and stats["batches"] > 0
            assert stats["rows_per_sec"] > 0
            # per-client queue attribution (the consumer= satellite)
            assert "trainer-0" in queue_seconds_by_consumer()
            # byte-identity through the full loader: collate output equals
            # the local loader's
            local_it = t.scan().batch_size(2048).to_jax_iter(
                device_put=False, drop_remainder=False
            )
            remote_it = scan.to_jax_iter(device_put=False, drop_remainder=False)
            for rb, lb in zip(remote_it, local_it):
                assert set(rb) == set(lb)
                for k in rb:
                    np.testing.assert_array_equal(rb[k], lb[k])
        finally:
            plane.close()

    def test_to_batches_and_to_arrow_route_remote(self, tmp_path):
        catalog, t = _make_table(tmp_path)
        plane = _Plane(catalog, tmp_path)
        try:
            client = ScanPlaneClient(plane.location)
            scan = t.scan().batch_size(4096).via_scanplane(client)
            local = list(t.scan().batch_size(4096).to_batches())
            got = list(scan.to_batches())
            assert all(a.equals(b) for a, b in zip(got, local))
            assert len(got) == len(local)
            # limit + skip stay client-side and exact
            lim = list(scan.limit(5000).to_batches())
            assert sum(b.num_rows for b in lim) == 5000
            assert scan.to_arrow().equals(
                pa.Table.from_batches(local)
            )
        finally:
            plane.close()

    def test_torch_adapter_rides_the_seam(self, tmp_path, monkeypatch):
        import types

        tud = types.ModuleType("torch.utils.data")

        class _IterableDataset:
            pass

        tud.IterableDataset = _IterableDataset
        torch_mod = types.ModuleType("torch")
        utils_mod = types.ModuleType("torch.utils")
        utils_mod.data = tud
        torch_mod.utils = utils_mod
        monkeypatch.setitem(sys.modules, "torch", torch_mod)
        monkeypatch.setitem(sys.modules, "torch.utils", utils_mod)
        monkeypatch.setitem(sys.modules, "torch.utils.data", tud)

        catalog, t = _make_table(tmp_path)
        plane = _Plane(catalog, tmp_path)
        try:
            client = ScanPlaneClient(plane.location)
            local = list(t.scan().batch_size(4096).to_torch())
            remote = list(
                t.scan().batch_size(4096).via_scanplane(client).to_torch()
            )
            assert len(remote) == len(local) > 0
            assert all(a.equals(b) for a, b in zip(remote, local))
        finally:
            plane.close()

    def test_ray_adapter_fans_out_per_range(self, tmp_path, monkeypatch):
        # wire-faithful ray stub (test_adapters contract)
        import types
        from collections.abc import Mapping

        import pandas as pd

        class _StubDataset:
            def __init__(self, rows):
                self.rows = rows

            def map_batches(self, fn, *, batch_size=None, batch_format="pandas"):
                out = []
                size = batch_size or max(1, len(self.rows))
                for start in range(0, len(self.rows), size):
                    df = pd.DataFrame(self.rows[start:start + size])
                    result = fn(df)
                    out.extend(result.to_pylist())
                return _StubDataset(out)

            def to_arrow(self):
                return pa.Table.from_pylist(self.rows)

        ray = types.ModuleType("ray")
        ray_data = types.ModuleType("ray.data")
        ray_data.from_items = lambda items: _StubDataset(
            [dict(it) if isinstance(it, Mapping) else {"item": it} for it in items]
        )
        ray.data = ray_data
        monkeypatch.setitem(sys.modules, "ray", ray)
        monkeypatch.setitem(sys.modules, "ray.data", ray_data)

        from lakesoul_tpu.data.ray_adapter import read_lakesoul

        catalog, t = _make_table(tmp_path)
        plane = _Plane(catalog, tmp_path)
        try:
            client = ScanPlaneClient(plane.location)
            scan = t.scan().batch_size(4096).via_scanplane(client)
            ds = read_lakesoul(scan)
            got = ds.to_arrow().sort_by("id")
            want = t.to_arrow().sort_by("id")
            assert got.num_rows == want.num_rows
            assert got.column("id").to_pylist() == want.column("id").to_pylist()
            assert got.column("v").to_pylist() == want.column("v").to_pylist()
        finally:
            plane.close()


# --------------------------------------------------------- subprocess smoke


class TestServiceEntrySmoke:
    def test_service_entry_serves_a_drive_client(self, tmp_path):
        """Quick tier-1 smoke of the REAL deployable entry: service role
        (gateway + 1 worker child) plus the drive role as a verification
        client — sha-identical to the in-process scan.  The SIGKILL chaos
        variants live in test_scanplane_chaos.py (slow)."""
        catalog, t = _make_table(tmp_path, rows=8000)
        env = dict(os.environ, JAX_PLATFORMS="cpu", PYTHONPATH=REPO)
        svc = subprocess.Popen(
            [sys.executable, "-m", "lakesoul_tpu.scanplane", "service",
             "--warehouse", str(tmp_path / "wh"),
             "--db-path", str(tmp_path / "meta.db"),
             "--workers", "1", "--spool", str(tmp_path / "spool"),
             "--lease-ttl-s", "5", "--poll-s", "0.05"],
            env=env, stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True,
        )
        try:
            handle = json.loads(svc.stdout.readline())
            drv = subprocess.run(
                [sys.executable, "-m", "lakesoul_tpu.scanplane", "drive",
                 "--location", handle["location"], "--table", "t",
                 "--batch-size", "4096"],
                env=env, capture_output=True, text=True, timeout=120,
            )
            assert drv.returncode == 0, drv.stderr[-2000:]
            out = json.loads(drv.stdout)
            assert out["rows"] == t.scan().count_rows()
            # sha of the remote stream == sha of the local stream
            import hashlib

            digest = hashlib.sha256()
            for b in t.scan().batch_size(4096).to_batches():
                sink = pa.BufferOutputStream()
                with pa.ipc.new_stream(sink, b.schema) as w:
                    w.write_batch(b)
                digest.update(sink.getvalue().to_pybytes())
            assert out["sha256"] == digest.hexdigest()
        finally:
            svc.terminate()
            try:
                svc.wait(10.0)
            except subprocess.TimeoutExpired:
                svc.kill()
