"""Process-level chaos for the scan plane (slow tier; the quick smoke of
the same entries runs in test_scanplane.py::TestServiceEntrySmoke).

The acceptance contract, proven with real OS processes sharing one
warehouse + spool:

- SIGKILL a scan-plane worker that is mid-range and HOLDING its lease →
  a peer worker takes the range over within one lease TTL, and a fleet of
  concurrent trainer clients completes with **exactly-once** delivery:
  every client's stream is byte-identical to the single-process
  ``scan.shard(rank, world)`` scan — no duplicate, no missing batches.
- The killed child is the REAL worker entry point
  (``python -m lakesoul_tpu.scanplane worker``), the same process the
  service role spawns — what is tested is what deploys."""

from __future__ import annotations

import os
import pathlib
import signal
import subprocess
import sys
import threading
import time

import numpy as np
import pyarrow as pa
import pytest

from lakesoul_tpu import LakeSoulCatalog
from lakesoul_tpu.scanplane import spool as spool_mod
from lakesoul_tpu.scanplane.client import ScanPlaneClient
from lakesoul_tpu.scanplane.delivery import ScanPlaneDelivery
from lakesoul_tpu.scanplane.session import ScanSession
from lakesoul_tpu.service.flight import LakeSoulFlightServer

REPO = str(pathlib.Path(__file__).resolve().parent.parent)
SCHEMA = pa.schema([("id", pa.int64()), ("v", pa.float64()), ("p", pa.string())])
TTL_S = 2.0
N_CLIENTS = 8

pytestmark = pytest.mark.slow


def _child_env(**extra) -> dict:
    env = dict(os.environ)
    env.update({
        "JAX_PLATFORMS": "cpu",
        "PYTHONPATH": REPO,
        "LAKESOUL_RETRY_SEED": "7",
    })
    env.update(extra)
    return env


def _spawn_worker(wh, db, spool, *, worker_id, **extra_env) -> subprocess.Popen:
    return subprocess.Popen(
        [
            sys.executable, "-m", "lakesoul_tpu.scanplane", "worker",
            "--warehouse", wh, "--db-path", db, "--spool", spool,
            "--lease-ttl-s", str(TTL_S), "--poll-s", "0.05",
            "--worker-id", worker_id,
        ],
        env=_child_env(**extra_env),
        stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True, cwd=REPO,
    )


class TestSigkillWorkerTakeover:
    def test_peer_takes_over_leased_range_exactly_once_delivery(self, tmp_path):
        wh, db = str(tmp_path / "wh"), str(tmp_path / "meta.db")
        catalog = LakeSoulCatalog(wh, db_path=db)
        t = catalog.create_table(
            "t", SCHEMA, primary_keys=["id"], range_partitions=["p"],
            hash_bucket_num=2,
        )
        rng = np.random.default_rng(3)
        for wave in range(3):
            for part, base in (("a", 0.0), ("b", 1000.0)):
                ids = np.sort(
                    rng.choice(40_000, 12_000, replace=False)
                ).astype(np.int64)
                t.upsert(pa.table({
                    "id": ids,
                    "v": base + rng.normal(size=len(ids)),
                    "p": np.repeat(part, len(ids)),
                }, schema=SCHEMA))

        spool = str(tmp_path / "spool")
        os.makedirs(spool)
        delivery = ScanPlaneDelivery(catalog, spool, wait_s=90)
        server = LakeSoulFlightServer(
            catalog, "grpc://127.0.0.1:0", scanplane=delivery
        )
        threading.Thread(target=server.serve, daemon=True).start()
        location = f"grpc://127.0.0.1:{server.port}"

        req = {"table": "t", "batch_size": 4096}
        session = ScanSession.plan(catalog, req)
        session.publish(spool)
        nranges = len(session.ranges)
        assert nranges >= 4  # 2 partitions x 2 buckets
        store = catalog.client.store
        keys = [f"scanplane/{session.session_id}/{i}" for i in range(nranges)]

        # the victim hangs INSIDE its first leased range (holding the
        # lease) — the most destructive SIGKILL window
        victim = _spawn_worker(
            wh, db, spool, worker_id="victim",
            LAKESOUL_FAULTS="scanplane.range:1:hang:300",
        )
        peer = None
        try:
            held_key = None
            deadline = time.monotonic() + 60.0
            while time.monotonic() < deadline and held_key is None:
                for k in keys:
                    lease = store.get_lease(k)
                    if lease is not None and lease.holder == "victim":
                        held_key = k
                        assert lease.fencing_token == 1
                        break
                if victim.poll() is not None:
                    _, err = victim.communicate(timeout=10.0)
                    pytest.fail(f"victim exited early: {err[-2000:]}")
                time.sleep(0.05)
            assert held_key is not None, "victim never leased a range"
            held_index = int(held_key.rsplit("/", 1)[-1])

            # trainer fleet starts consuming BEFORE the kill: rank r of 8
            results: dict[int, list] = {r: [] for r in range(N_CLIENTS)}
            errors: list = []
            threads = []

            def consume(rank):
                try:
                    c = ScanPlaneClient(location)
                    for b in c.iter_batches(req, rank=rank, world=N_CLIENTS):
                        results[rank].append(b)
                except BaseException as e:
                    errors.append((rank, e))

            for r in range(N_CLIENTS):
                th = threading.Thread(target=consume, args=(r,), daemon=True)
                th.start()
                threads.append(th)

            # peer worker runs alongside; it produces every OTHER range but
            # cannot touch the victim's until the lease expires
            peer = _spawn_worker(wh, db, spool, worker_id="peer")
            victim.send_signal(signal.SIGKILL)
            victim.wait(10.0)
            killed_at = time.monotonic()

            sdir = session.dir(spool)
            deadline = time.monotonic() + 60.0
            while time.monotonic() < deadline:
                if spool_mod.range_ready(sdir, held_index):
                    break
                time.sleep(0.02)
            assert spool_mod.range_ready(sdir, held_index), (
                "peer never produced the victim's range"
            )
            takeover_latency = time.monotonic() - killed_at
            # "within one lease TTL": expiry <= TTL after the kill; poll
            # cadence + the decode itself add the small remainder
            assert takeover_latency < TTL_S + 4.0, takeover_latency
            # the fencing trail proves the takeover: token 2 on the
            # victim's range, and the sidecar records the peer as producer
            side = spool_mod.read_sidecar(sdir, held_index)
            assert side["fence"] == 2
            assert side["worker"] == "peer"

            for th in threads:
                th.join(90.0)
            assert not errors, errors

            # EXACTLY-ONCE: every client's stream is byte-identical to the
            # single-process shard scan — no duplicate, no missing batches
            total = 0
            for r in range(N_CLIENTS):
                want = list(
                    t.scan().batch_size(4096).shard(r, N_CLIENTS).to_batches()
                )
                got = results[r]
                assert len(got) == len(want), (r, len(got), len(want))
                for a, b in zip(got, want):
                    assert a.equals(b)
                total += sum(b.num_rows for b in got)
            assert total == t.scan().count_rows()
        finally:
            if victim.poll() is None:
                victim.kill()
            if peer is not None:
                peer.terminate()
                try:
                    peer.wait(10.0)
                except subprocess.TimeoutExpired:
                    peer.kill()
            server.shutdown()
