"""Compaction service, cleaner, and CDC streaming tests."""

import numpy as np
import pyarrow as pa
import pytest

from lakesoul_tpu import LakeSoulCatalog
from lakesoul_tpu.compaction import Cleaner, CompactionService
from lakesoul_tpu.meta.store import COMPACTION_TRIGGER_VERSION_GAP
from lakesoul_tpu.streaming import CdcIngestor, CheckpointedWriter
from lakesoul_tpu.streaming.cdc import checkpoint_commit_id


SCHEMA = pa.schema([("id", pa.int64()), ("v", pa.float64())])


@pytest.fixture()
def catalog(tmp_warehouse):
    return LakeSoulCatalog(str(tmp_warehouse))


class TestCompactionService:
    def test_trigger_fires_and_compacts(self, catalog):
        t = catalog.create_table("t", SCHEMA, primary_keys=["id"], hash_bucket_num=1)
        svc = CompactionService(catalog, workers=1, min_file_num=2)
        svc.start()
        try:
            # enough commits to cross the version-gap trigger
            for i in range(COMPACTION_TRIGGER_VERSION_GAP + 1):
                t.write_arrow(pa.table({"id": [i], "v": [float(i)]}))
            svc.drain()
        finally:
            svc.stop()
        assert svc.stats.triggered >= 1
        assert svc.stats.compacted >= 1
        plan = t.scan().scan_plan()
        # post-compaction: merge no longer needed on the compacted head
        assert any(u.primary_keys == [] for u in plan)
        got = t.to_arrow().sort_by("id")
        assert got.num_rows == COMPACTION_TRIGGER_VERSION_GAP + 1

    def test_sweep_compacts_without_trigger(self, catalog):
        t = catalog.create_table("s", SCHEMA, primary_keys=["id"], hash_bucket_num=1)
        t.write_arrow(pa.table({"id": [1], "v": [1.0]}))
        t.write_arrow(pa.table({"id": [2], "v": [2.0]}))
        svc = CompactionService(catalog, min_file_num=2)
        assert svc.sweep() == 1
        assert svc.sweep() == 0  # idempotent


class TestCleaner:
    def test_expired_versions_and_files_removed(self, catalog, tmp_path):
        import os

        t = catalog.create_table("c", SCHEMA, primary_keys=["id"], hash_bucket_num=1)
        t.write_arrow(pa.table({"id": [1], "v": [1.0]}))
        t.write_arrow(pa.table({"id": [2], "v": [2.0]}))
        old_files = [u for unit in t.scan().scan_plan() for u in unit.data_files]
        t.compact()
        # age everything: pretend the clock advanced past retention
        future = 10**14
        cleaner = Cleaner(catalog, retention_ms=1, discard_grace_ms=1)
        result = cleaner.clean_table("c", now_ms=future)
        assert result["versions_dropped"] >= 2
        n_discard = cleaner.clean_discarded_files(now_ms=future)
        assert n_discard == len(old_files)
        for f in old_files:
            assert not os.path.exists(f)
        # table still reads correctly from the compacted head
        got = t.to_arrow().sort_by("id")
        assert got.column("id").to_pylist() == [1, 2]

    def test_recent_data_untouched(self, catalog):
        t = catalog.create_table("r", SCHEMA, primary_keys=["id"], hash_bucket_num=1)
        t.write_arrow(pa.table({"id": [1], "v": [1.0]}))
        cleaner = Cleaner(catalog)  # default 7-day retention
        result = cleaner.clean_table("r")
        assert result == {"versions_dropped": 0, "files_deleted": 0}


class TestCheckpointedWriter:
    def test_exactly_once_replay(self, catalog):
        t = catalog.create_table("ck", SCHEMA, primary_keys=["id"], hash_bucket_num=1)
        w = CheckpointedWriter(t)
        w.write(pa.table({"id": [1, 2], "v": [1.0, 2.0]}))
        assert w.checkpoint(1) == 1
        # replay of the same epoch with the same data: no-op
        w.write(pa.table({"id": [1, 2], "v": [1.0, 2.0]}))
        assert w.checkpoint(1) == 0
        head = catalog.client.store.get_latest_partition_info(t.info.table_id, "-5")
        assert head.version == 0  # only one commit landed
        assert t.to_arrow().num_rows == 2

    def test_replay_deletes_restaged_orphans(self, catalog, tmp_path):
        # ADVICE r1: a replayed checkpoint re-stages fresh parquet files under
        # new names; since the commit id is already durable they are skipped —
        # they must be deleted, not silently orphaned on the object store
        import glob

        t = catalog.create_table("cko", SCHEMA, primary_keys=["id"], hash_bucket_num=1)
        w = CheckpointedWriter(t)
        w.write(pa.table({"id": [1, 2], "v": [1.0, 2.0]}))
        assert w.checkpoint(1) == 1
        files_after_commit = set(glob.glob(f"{t.info.table_path}/**/*.parquet", recursive=True))
        w.write(pa.table({"id": [1, 2], "v": [1.0, 2.0]}))
        assert w.checkpoint(1) == 0  # replay
        files_after_replay = set(glob.glob(f"{t.info.table_path}/**/*.parquet", recursive=True))
        assert files_after_replay == files_after_commit

    def test_multiple_epochs_accumulate(self, catalog):
        t = catalog.create_table("ck2", SCHEMA, primary_keys=["id"], hash_bucket_num=1)
        w = CheckpointedWriter(t)
        w.write(pa.table({"id": [1], "v": [1.0]}))
        w.checkpoint(1)
        w.write(pa.table({"id": [2], "v": [2.0]}))
        w.checkpoint(2)
        assert t.to_arrow().num_rows == 2

    def test_commit_id_deterministic(self):
        a = checkpoint_commit_id("tid", "-5", 7)
        b = checkpoint_commit_id("tid", "-5", 7)
        c = checkpoint_commit_id("tid", "-5", 8)
        assert a == b and a != c


class TestCdcIngestor:
    def test_cdc_stream_end_to_end(self, catalog):
        t = catalog.create_table("cdc", SCHEMA, primary_keys=["id"], cdc=True, hash_bucket_num=1)
        ing = CdcIngestor(t)
        ing.apply_many(
            [
                ("insert", {"id": 1, "v": 1.0}),
                ("insert", {"id": 2, "v": 2.0}),
                ("update", {"id": 1, "v": 10.0}),
            ]
        )
        ing.checkpoint(1)
        ing.apply("delete", {"id": 2})
        ing.checkpoint(2)
        got = t.to_arrow()
        assert got.column("id").to_pylist() == [1]
        assert got.column("v").to_pylist() == [10.0]
        # incremental CDC consumers see the delete row kind
        raw = t.scan().with_cdc_deletes().to_arrow().sort_by("id")
        kinds = dict(zip(raw.column("id").to_pylist(), raw.column(t.info.cdc_column).to_pylist()))
        assert kinds[2] == "delete"

    def test_requires_cdc_table(self, catalog):
        from lakesoul_tpu.errors import ConfigError

        t = catalog.create_table("plain", SCHEMA, primary_keys=["id"])
        with pytest.raises(ConfigError, match="not CDC-enabled"):
            CdcIngestor(t)

    def test_online_feature_pipeline(self, catalog):
        """BASELINE.json config 5: CDC upserts → incremental read → JAX
        feature pipeline."""
        import time

        import jax.numpy as jnp

        t = catalog.create_table("feat", SCHEMA, primary_keys=["id"], cdc=True, hash_bucket_num=1)
        ing = CdcIngestor(t)
        ing.apply_many([("insert", {"id": i, "v": float(i)}) for i in range(10)])
        ing.checkpoint(1)
        ts0 = max(
            p.timestamp
            for p in catalog.client.store.get_all_latest_partition_info(t.info.table_id)
        )
        time.sleep(0.002)
        ing.apply_many([("update", {"id": 3, "v": 33.0}), ("insert", {"id": 99, "v": 99.0})])
        ing.checkpoint(2)
        # incremental read of just the new epoch → features on device
        inc = t.scan().incremental(ts0).to_arrow().sort_by("id")
        assert inc.column("id").to_pylist() == [3, 99]
        feats = jnp.asarray(inc.column("v").to_numpy(zero_copy_only=False))
        assert float(feats.sum()) == 132.0


class TestAutoFlushCheckpointInteraction:
    def test_checkpoint_commits_auto_flushed_files(self, catalog):
        # write_batch auto-flushes on the row budget; the checkpoint must
        # commit those files too, not just the final flush's
        t = catalog.create_table("af", SCHEMA, primary_keys=["id"], hash_bucket_num=1)
        w = CheckpointedWriter(t)
        w._ensure_writer().config.max_file_rows = 50
        for i in range(5):
            w.write(pa.table({"id": np.arange(i * 40, (i + 1) * 40), "v": np.zeros(40)}))
        assert w.checkpoint(1) >= 1
        assert t.to_arrow().num_rows == 200  # every auto-flushed file committed

    def test_abort_after_checkpoint_keeps_committed_files(self, catalog):
        t = catalog.create_table("af2", SCHEMA, primary_keys=["id"], hash_bucket_num=1)
        w = CheckpointedWriter(t)
        w.write(pa.table({"id": [1], "v": [1.0]}))
        w.checkpoint(1)
        w.write(pa.table({"id": [2], "v": [2.0]}))
        w.abort()  # must only discard the uncommitted epoch
        assert t.to_arrow().column("id").to_pylist() == [1]


class TestFollowSource:
    def test_follow_yields_new_commits(self, catalog):
        import threading
        import time as _t

        t = catalog.create_table("fw", SCHEMA, primary_keys=["id"], hash_bucket_num=1)
        t.write_arrow(pa.table({"id": [1], "v": [1.0]}))  # before follow start
        stop = threading.Event()
        seen: list[int] = []
        start_ts = catalog.client.store.get_latest_partition_info(
            t.info.table_id, "-5"
        ).timestamp

        def consume():
            for batch in t.scan().follow(start_ts, poll_interval=0.05, stop_event=stop):
                seen.extend(batch.column("id").to_pylist())
                if len(seen) >= 3:
                    stop.set()

        th = threading.Thread(target=consume, daemon=True)
        th.start()
        _t.sleep(0.05)
        t.write_arrow(pa.table({"id": [2, 3], "v": [2.0, 3.0]}))
        _t.sleep(0.1)
        t.write_arrow(pa.table({"id": [4], "v": [4.0]}))
        th.join(timeout=10)
        stop.set()
        assert sorted(seen)[:3] == [2, 3, 4][:3]
        assert 1 not in seen  # pre-start data excluded

    def test_follow_stops_on_event(self, catalog):
        import threading

        t = catalog.create_table("fw2", SCHEMA)
        stop = threading.Event()
        stop.set()
        assert list(t.scan().follow(stop_event=stop, poll_interval=0.01)) == []

    def test_poll_cost_is_o_new_commits(self, catalog):
        """VERDICT r1 #10 'done' criterion: an idle poll costs one head query
        and zero version-history reads; a poll after one commit reads only
        that partition's new versions."""
        t = catalog.create_table("fwc", SCHEMA, primary_keys=["id"], hash_bucket_num=2)
        for i in range(5):
            t.write_arrow(pa.table({"id": [i], "v": [float(i)]}))
        client = catalog.client

        calls: dict[str, int] = {}
        store = client.store

        class CountingStore:
            def __getattr__(self, name):
                attr = getattr(store, name)
                if callable(attr):
                    def wrapper(*a, **k):
                        calls[name] = calls.get(name, 0) + 1
                        return attr(*a, **k)

                    return wrapper
                return attr

        from lakesoul_tpu.meta.entity import now_millis

        cursors = client.init_follow_cursors(t.info.table_name, now_millis())
        client.store = CountingStore()
        try:
            # idle polls: head listing only, no version-history or commit reads
            for _ in range(3):
                assert client.poll_scan_plan(t.info.table_name, cursors) == []
            assert calls.get("get_all_latest_partition_info") == 3
            assert calls.get("get_partition_versions", 0) == 0
            assert calls.get("get_data_commit_info", 0) == 0

            calls.clear()
            client.store = store
            t.write_arrow(pa.table({"id": [100], "v": [1.0]}))
            client.store = CountingStore()
            units = client.poll_scan_plan(t.info.table_name, cursors)
            assert len(units) == 1 and len(units[0].data_files) == 1
            # exactly one partition re-read its (new) version tail
            assert calls.get("get_partition_versions") == 1
            assert calls.get("get_data_commit_info") == 1

            # and the cursor advanced: the same commit is not re-delivered
            calls.clear()
            assert client.poll_scan_plan(t.info.table_name, cursors) == []
            assert calls.get("get_partition_versions", 0) == 0
        finally:
            client.store = store


class TestPrometheusMetrics:
    def test_exposition_format(self, catalog):
        from lakesoul_tpu.service.flight import LakeSoulFlightClient, LakeSoulFlightServer

        t = catalog.create_table("pm", SCHEMA)
        t.write_arrow(pa.table({"id": [1], "v": [1.0]}))
        server = LakeSoulFlightServer(catalog, "grpc://127.0.0.1:0")
        try:
            client = LakeSoulFlightClient(f"grpc://127.0.0.1:{server.port}")
            client.scan("pm")
            text = client.action("metrics_prometheus")[0].decode()
            assert "# TYPE lakesoul_flight_rows_out counter" in text
            assert "lakesoul_flight_rows_out 1" in text
            assert "# TYPE lakesoul_flight_active_get_streams gauge" in text
        finally:
            server.shutdown()

    def test_follow_cursor_never_moves_backwards(self, catalog):
        # first poll right after start: upper = now-1 < cursor must not
        # rewind the window onto pre-start commits
        import threading
        import time as _t

        t = catalog.create_table("fw3", SCHEMA, primary_keys=["id"], hash_bucket_num=1)
        t.write_arrow(pa.table({"id": [1], "v": [1.0]}))
        start_ts = catalog.client.store.get_latest_partition_info(
            t.info.table_id, "-5"
        ).timestamp
        stop = threading.Event()
        seen = []

        def consume():
            # poll aggressively so the first window lands in the same ms
            for batch in t.scan().follow(start_ts, poll_interval=0.001, stop_event=stop):
                seen.extend(batch.column("id").to_pylist())

        th = threading.Thread(target=consume, daemon=True)
        th.start()
        _t.sleep(0.3)  # many empty polls before any new commit
        t.write_arrow(pa.table({"id": [2], "v": [2.0]}))
        deadline = _t.time() + 5
        while 2 not in seen and _t.time() < deadline:
            _t.sleep(0.02)
        stop.set()
        th.join(timeout=5)
        assert 1 not in seen  # pre-start commit never leaked
        assert 2 in seen


class TestDataAssets:
    def test_counts_match_metadata(self, catalog):
        from lakesoul_tpu.service.assets import count_data_assets

        t = catalog.create_table("as1", SCHEMA, primary_keys=["id"], hash_bucket_num=2)
        t.write_arrow(pa.table({"id": [1, 2, 3, 4], "v": [1.0, 2.0, 3.0, 4.0]}))
        t.upsert(pa.table({"id": [2], "v": [20.0]}))
        catalog.create_table("as2", SCHEMA)

        report = count_data_assets(catalog)
        by_name = {r.table_name: r for r in report.tables}
        a = by_name["as1"]
        assert a.partitions == 1
        assert a.total_commits == 2  # initial write + upsert
        live = [f for u in t.scan().scan_plan() for f in u.data_files]
        assert a.live_files == len(live)
        assert a.live_bytes > 0
        assert by_name["as2"].live_files == 0

        ns = report.by_namespace()
        row = {c: ns.column(c)[0].as_py() for c in ns.column_names}
        assert row["tables"] == 2 and row["live_files"] == a.live_files

    def test_assets_over_flight(self, catalog):
        from lakesoul_tpu.service.flight import LakeSoulFlightClient, LakeSoulFlightServer

        t = catalog.create_table("as3", SCHEMA)
        t.write_arrow(pa.table({"id": [1], "v": [1.0]}))
        server = LakeSoulFlightServer(catalog, "grpc://127.0.0.1:0")
        try:
            client = LakeSoulFlightClient(f"grpc://127.0.0.1:{server.port}")
            raw = client.action("data_assets")[0]
            report = pa.ipc.open_stream(raw).read_all()
            names = report.column("table_name").to_pylist()
            assert "as3" in names
        finally:
            server.shutdown()


class TestFollowResume:
    def test_cursor_state_round_trips_and_resumes(self, catalog):
        """A restarted follow() with persisted cursors continues exactly
        after the last delivered commit (pending-splits checkpointing)."""
        import threading

        from lakesoul_tpu.meta.client import (
            follow_cursors_from_json,
            follow_cursors_to_json,
        )
        from lakesoul_tpu.meta.entity import now_millis

        t = catalog.create_table("fres", SCHEMA, primary_keys=["id"], hash_bucket_num=1)
        t.write_arrow(pa.table({"id": [1], "v": [1.0]}))  # pre-start

        cursors = catalog.client.init_follow_cursors(t.info.table_name, now_millis())
        stop = threading.Event()

        def drain(cur):
            seen = []
            stop.clear()
            gen = t.scan().follow(poll_interval=0.01, stop_event=stop, cursors=cur)
            for batch in gen:
                seen.extend(batch.column("id").to_pylist())
                if seen:
                    stop.set()
            return seen

        t.write_arrow(pa.table({"id": [2], "v": [2.0]}))
        first = drain(cursors)
        assert first == [2]

        # "restart": serialize, drop everything, restore
        state = follow_cursors_to_json(cursors)
        restored = follow_cursors_from_json(state)
        t.write_arrow(pa.table({"id": [3], "v": [3.0]}))
        second = drain(restored)
        assert second == [3]  # no replay of 2, no loss of 3


class TestCleanerTtlProperties:
    """partition.ttl = partition DATA lifetime (the reference's semantics:
    expired partitions are deleted outright); lakesoul.version.retention =
    snapshot-history retention override."""

    def test_version_retention_property_overrides_default(self, catalog):
        t = catalog.create_table(
            "vr0", SCHEMA, primary_keys=["id"], hash_bucket_num=1,
            properties={"lakesoul.version.retention": "0"},
        )
        t.write_arrow(pa.table({"id": [1], "v": [1.0]}))
        t.write_arrow(pa.table({"id": [2], "v": [2.0]}))
        t.compact()
        import time

        time.sleep(0.002)
        # default retention (7 days) would keep everything; the property wins
        result = Cleaner(catalog).clean_table("vr0")
        assert result["versions_dropped"] >= 2
        # history trimmed, data intact
        assert t.to_arrow().sort_by("id").column("id").to_pylist() == [1, 2]

    def test_partition_ttl_expires_data(self, catalog):
        import os
        import time

        t = catalog.create_table(
            "pttl", SCHEMA, primary_keys=["id"], hash_bucket_num=1,
            properties={"partition.ttl": "0"},
        )
        t.write_arrow(pa.table({"id": [1], "v": [1.0]}))
        files = [f for u in t.scan().scan_plan() for f in u.data_files]
        time.sleep(0.002)
        n = Cleaner(catalog).expire_partitions("pttl")
        assert n == 1
        assert t.to_arrow().num_rows == 0  # partition data gone
        for f in files:
            assert not os.path.exists(f)

    def test_fresh_partitions_survive_ttl(self, catalog):
        t = catalog.create_table(
            "pttl2", SCHEMA, primary_keys=["id"], hash_bucket_num=1,
            properties={"partition.ttl": "7"},  # a week: nothing expires now
        )
        t.write_arrow(pa.table({"id": [1], "v": [1.0]}))
        assert Cleaner(catalog).expire_partitions("pttl2") == 0
        assert t.to_arrow().num_rows == 1

    @pytest.mark.parametrize("bad", ["soon", "-1", "inf", "nan"])
    def test_invalid_ttl_values_never_destroy_data(self, catalog, bad, caplog):
        import logging

        name = f"ttlbad_{bad}"
        t = catalog.create_table(
            name, SCHEMA, primary_keys=["id"], hash_bucket_num=1,
            properties={"partition.ttl": bad, "lakesoul.version.retention": bad},
        )
        t.write_arrow(pa.table({"id": [1], "v": [1.0]}))
        with caplog.at_level(logging.WARNING, logger="lakesoul_tpu.compaction.cleaner"):
            cleaner = Cleaner(catalog)
            assert cleaner.expire_partitions(name) == 0
            result = cleaner.clean_table(name)
        assert result == {"versions_dropped": 0, "files_deleted": 0}
        assert t.to_arrow().num_rows == 1
        assert any("ttl" in r.getMessage() or "retention" in r.getMessage()
                   for r in caplog.records)
