"""Spark Murmur3 (seed 42) compatibility tests.

Anchor values come from Apache Spark's HashExpressionsSuite /
`spark.sql("select hash(x)")` semantics, which the reference reproduces
(rust/lakesoul-io/src/utils/hash/spark_murmur3.rs).  Interpreted as int32.
"""

import numpy as np
import pyarrow as pa
import pytest

from lakesoul_tpu.utils import spark_hash as sh


def as_i32(u):
    return int(np.int64(int(u)) - (1 << 32) if int(u) >= 1 << 31 else int(u))


def reference_scalar_murmur(data: bytes, seed: int = 42) -> int:
    """Independent straightforward scalar implementation used to cross-check
    the vectorized one (tail processed byte-by-byte, Spark style)."""

    def mix_k(k):
        k = (k * 0xCC9E2D51) & 0xFFFFFFFF
        k = ((k << 15) | (k >> 17)) & 0xFFFFFFFF
        return (k * 0x1B873593) & 0xFFFFFFFF

    h = seed
    n = len(data)
    for i in range(0, n - n % 4, 4):
        k = int.from_bytes(data[i : i + 4], "little")
        h ^= mix_k(k)
        h = ((h << 13) | (h >> 19)) & 0xFFFFFFFF
        h = (h * 5 + 0xE6546B64) & 0xFFFFFFFF
    for b in data[n - n % 4 :]:
        h ^= mix_k(b)
        h = ((h << 13) | (h >> 19)) & 0xFFFFFFFF
        h = (h * 5 + 0xE6546B64) & 0xFFFFFFFF
    h ^= n
    h ^= h >> 16
    h = (h * 0x85EBCA6B) & 0xFFFFFFFF
    h ^= h >> 13
    h = (h * 0xC2B2AE35) & 0xFFFFFFFF
    h ^= h >> 16
    return h


class TestScalarAnchors:
    def test_spark_hash_int_anchors(self):
        # spark.sql("select hash(0)") == 933211791, hash(1) == -559580957
        assert as_i32(sh.hash_scalar(0, pa.int32())) == 933211791
        assert as_i32(sh.hash_scalar(1, pa.int32())) == -559580957

    def test_cross_check_scalar_vs_reference_impl(self):
        rng = np.random.default_rng(7)
        for _ in range(50):
            n = int(rng.integers(0, 37))
            data = bytes(rng.integers(0, 256, size=n, dtype=np.uint8))
            assert sh.murmur3_bytes(data) == reference_scalar_murmur(data)

    def test_string_hash_matches_bytes(self):
        s = "hello lakesoul"
        assert sh.hash_scalar(s) == sh.murmur3_bytes(s.encode())


class TestVectorized:
    def test_int32_matches_scalar(self):
        vals = np.array([0, 1, -1, 42, 2**31 - 1, -(2**31)], dtype=np.int32)
        vec = sh.hash_int_array(vals)
        for v, h in zip(vals, vec):
            # sign-extended to u32, 4 LE bytes
            b = int(np.int64(v) & 0xFFFFFFFF).to_bytes(4, "little")
            assert int(h) == reference_scalar_murmur(b)

    def test_int64_matches_scalar(self):
        vals = np.array([0, 1, -1, 2**40, -(2**40)], dtype=np.int64)
        vec = sh.hash_long_array(vals)
        for v, h in zip(vals, vec):
            b = int(np.int64(v).astype(np.uint64) if hasattr(np.int64(v), "astype") else v)
            raw = (int(v) & 0xFFFFFFFFFFFFFFFF).to_bytes(8, "little")
            assert int(h) == reference_scalar_murmur(raw)

    def test_small_ints_sign_extend(self):
        # i8 -1 must hash like u32 0xFFFFFFFF (the reference casts `v as u32`)
        v8 = sh.hash_array(pa.array([-1], type=pa.int8()))
        v32 = sh.hash_array(pa.array([-1], type=pa.int32()))
        assert int(v8[0]) == int(v32[0])

    def test_float_negative_zero(self):
        h_neg = sh.hash_float_array(np.array([-0.0], dtype=np.float64))
        h_zero_int = sh.hash_long_array(np.array([0], dtype=np.int64))
        assert int(h_neg[0]) == int(h_zero_int[0])

    def test_strings_grouped_by_length(self):
        vals = ["", "a", "ab", "abc", "abcd", "abcde", "hello world!", "a", "abcd"]
        arr = pa.array(vals)
        vec = sh.hash_array(arr)
        for v, h in zip(vals, vec):
            assert int(h) == reference_scalar_murmur(v.encode())
        assert vec[1] == vec[7] and vec[4] == vec[8]

    def test_nulls_leave_buffer_unchanged(self):
        arr = pa.array([1, None, 3], type=pa.int32())
        # first-column nulls keep the zero-initialized buffer → hash 0
        # (reference: repartition/mod.rs resizes the buffer with 0 and nulls
        # never update it; the dict-array test asserts hash 0 for nulls)
        h0 = sh.hash_columns([arr])
        assert int(h0[1]) == 0
        # chained column: null keeps the running hash from previous columns
        other = pa.array([7, 7, 7], type=pa.int32())
        h1 = sh.hash_columns([other, arr])
        base = sh.hash_columns([other])
        assert int(h1[1]) == int(base[1])

    def test_multi_column_chaining(self):
        a = pa.array([1, 2], type=pa.int32())
        b = pa.array(["x", "y"])
        h1 = sh.hash_columns([a])
        h2 = sh.hash_columns([a, b])
        assert not np.array_equal(h1, h2)
        # manual chain
        expect0 = reference_scalar_murmur(b"x", seed=int(h1[0]))
        assert int(h2[0]) == expect0

    def test_dictionary_matches_plain(self):
        vals = ["foo", None, "bar", "foo", None]
        plain = sh.hash_array(pa.array(vals))
        dict_arr = pa.array(vals).dictionary_encode()
        assert np.array_equal(plain, sh.hash_array(dict_arr))


class TestBuckets:
    def test_bucket_range(self):
        h = sh.hash_columns([pa.array(np.arange(1000, dtype=np.int64))])
        b = sh.bucket_ids(h, 7)
        assert b.min() >= 0 and b.max() < 7

    def test_scalar_bucket_agrees_with_column_bucket(self):
        vals = pa.array([123, 456, 789], type=pa.int64())
        h = sh.hash_columns([vals])
        b = sh.bucket_ids(h, 16)
        for v, expect in zip(vals.to_pylist(), b):
            assert sh.bucket_id_for_scalar(v, 16, pa.int64()) == expect
