"""Mini SQL engine tests: parse + execute over lakehouse tables."""

import numpy as np
import pyarrow as pa
import pytest

from lakesoul_tpu import LakeSoulCatalog
from lakesoul_tpu.sql import SqlSession
from lakesoul_tpu.sql.parser import SqlError, parse


@pytest.fixture()
def session(tmp_warehouse):
    catalog = LakeSoulCatalog(str(tmp_warehouse))
    s = SqlSession(catalog)
    s.execute(
        "CREATE TABLE users (id bigint PRIMARY KEY, name string, age int, city string)"
        " WITH (hashBucketNum = '2')"
    )
    s.execute(
        "INSERT INTO users VALUES"
        " (1, 'alice', 30, 'sf'), (2, 'bob', 25, 'nyc'),"
        " (3, 'carol', 35, 'sf'), (4, 'dave', 28, 'nyc')"
    )
    return s


class TestParser:
    def test_select_parse(self):
        stmt = parse(
            "SELECT id, name AS n FROM t WHERE age > 20 AND city = 'sf'"
            " ORDER BY id DESC LIMIT 5"
        )
        assert stmt.table == "t" and stmt.limit == 5
        assert stmt.order_by == [("id", True)]
        assert stmt.where.op == "and"

    def test_string_escapes_and_floats(self):
        stmt = parse("SELECT a FROM t WHERE s = 'it''s' AND x >= -1.5")
        comps = stmt.where.args
        assert comps[0].value == "it's"
        assert comps[1].value == -1.5

    def test_errors(self):
        with pytest.raises(SqlError):
            parse("SELEC x FROM t")
        with pytest.raises(SqlError):
            parse("SELECT FROM t")
        with pytest.raises(SqlError):
            parse("SELECT a FROM t WHERE")
        with pytest.raises(SqlError):
            parse("SELECT a FROM t extra garbage")


class TestExecute:
    def test_select_where_order_limit(self, session):
        out = session.execute(
            "SELECT id, name FROM users WHERE city = 'sf' ORDER BY id"
        )
        assert out.column("id").to_pylist() == [1, 3]
        out2 = session.execute("SELECT * FROM users ORDER BY age DESC LIMIT 2")
        assert out2.column("name").to_pylist() == ["carol", "alice"]

    def test_in_and_null_predicates(self, session):
        session.execute("INSERT INTO users (id, name) VALUES (5, 'eve')")
        out = session.execute("SELECT id FROM users WHERE age IS NULL")
        assert out.column("id").to_pylist() == [5]
        out2 = session.execute("SELECT id FROM users WHERE id IN (2, 5) ORDER BY id")
        assert out2.column("id").to_pylist() == [2, 5]
        out3 = session.execute(
            "SELECT id FROM users WHERE id NOT IN (1, 2, 3, 5) AND age IS NOT NULL"
        )
        assert out3.column("id").to_pylist() == [4]

    def test_global_aggregates(self, session):
        out = session.execute("SELECT count(*) AS n, avg(age) AS a, max(age) FROM users")
        assert out.column("n").to_pylist() == [4]
        assert out.column("a").to_pylist() == [29.5]
        assert out.column("max(age)").to_pylist() == [35]

    def test_group_by(self, session):
        out = session.execute(
            "SELECT city, count(*) AS n, avg(age) AS mean_age FROM users"
            " GROUP BY city ORDER BY city"
        )
        assert out.column("city").to_pylist() == ["nyc", "sf"]
        assert out.column("n").to_pylist() == [2, 2]
        assert out.column("mean_age").to_pylist() == [26.5, 32.5]

    def test_group_by_null_key_counts_rows(self, session):
        # COUNT(*) over a group whose key is NULL must count rows, not
        # non-null key values (ADVICE r1)
        session.execute("INSERT INTO users (id, name, age) VALUES (6, 'f', 1), (7, 'g', 2)")
        out = session.execute(
            "SELECT city, count(*) AS n FROM users GROUP BY city ORDER BY n"
        )
        assert dict(zip(out.column("city").to_pylist(), out.column("n").to_pylist()))[None] == 2

    def test_two_count_stars_in_one_group_by(self, session):
        out = session.execute(
            "SELECT city, count(*) AS a, count(*) AS b FROM users GROUP BY city ORDER BY city"
        )
        assert out.column("a").to_pylist() == out.column("b").to_pylist() == [2, 2]

    def test_duplicate_aggregates_in_one_group_by(self, session):
        out = session.execute(
            "SELECT city, sum(age) AS a, sum(age) AS b FROM users GROUP BY city ORDER BY city"
        )
        assert out.column("a").to_pylist() == out.column("b").to_pylist() == [53, 65]

    def test_multi_key_order_by(self, session):
        session.execute("INSERT INTO users VALUES (8, 'hank', 30, 'nyc')")
        out = session.execute("SELECT age, id FROM users ORDER BY age DESC, id DESC")
        pairs = list(zip(out.column("age").to_pylist(), out.column("id").to_pylist()))
        assert pairs == sorted(pairs, key=lambda p: (-p[0], -p[1]))

    def test_upsert_semantics_via_insert(self, session):
        session.execute("INSERT INTO users VALUES (1, 'ALICE', 31, 'sf')")
        out = session.execute("SELECT name, age FROM users WHERE id = 1")
        assert out.column("name").to_pylist() == ["ALICE"]  # PK upsert merged

    def test_show_describe_drop(self, session):
        assert "users" in session.execute("SHOW TABLES").column("table_name").to_pylist()
        desc = session.execute("DESCRIBE users")
        assert desc.column("primary_key").to_pylist()[0] is True
        session.execute("DROP TABLE users")
        assert session.execute("SHOW TABLES").num_rows == 0
        assert session.execute("DROP TABLE IF EXISTS users").column("status").to_pylist() == ["absent"]

    def test_create_partitioned(self, session):
        session.execute(
            "CREATE TABLE ev (id bigint PRIMARY KEY, v double, day string)"
            " PARTITIONED BY (day)"
        )
        session.execute("INSERT INTO ev VALUES (1, 0.5, 'd1'), (2, 1.5, 'd2')")
        out = session.execute("SELECT id FROM ev WHERE day = 'd2'")
        assert out.column("id").to_pylist() == [2]
        t = session.catalog.table("ev")
        assert t.info.range_partition_columns == ["day"]


class TestSqlOverFlight:
    def test_sql_action(self, tmp_warehouse):
        from lakesoul_tpu.service.flight import LakeSoulFlightClient, LakeSoulFlightServer

        catalog = LakeSoulCatalog(str(tmp_warehouse))
        SqlSession(catalog).execute("CREATE TABLE t (id bigint PRIMARY KEY, v double)")
        server = LakeSoulFlightServer(catalog, "grpc://127.0.0.1:0")
        try:
            client = LakeSoulFlightClient(f"grpc://127.0.0.1:{server.port}")
            client.action("sql", {"statement": "INSERT INTO t VALUES (1, 2.5)"})
            raw = client.action("sql", {"statement": "SELECT * FROM t"})[0]
            result = pa.ipc.open_stream(raw).read_all()
            assert result.column("v").to_pylist() == [2.5]
        finally:
            server.shutdown()


class TestSqlConsole:
    def test_sql_in_console(self, tmp_warehouse):
        from lakesoul_tpu.service.console import Console

        c = Console(LakeSoulCatalog(str(tmp_warehouse)))
        c.execute("CREATE TABLE t (id bigint, v double)")
        c.execute("INSERT INTO t VALUES (1, 1.0), (2, 2.0)")
        out = c.execute("SELECT count(*) AS n FROM t")
        assert "2" in out
        assert "error" in c.execute("SELECT * FROM missing_table")


class TestAlterAndCall:
    def test_alter_add_column(self, session):
        session.execute("ALTER TABLE users ADD COLUMN score double")
        out = session.execute("SELECT id, score FROM users WHERE id = 1")
        assert out.column("score").to_pylist() == [None]
        session.execute("INSERT INTO users (id, name, score) VALUES (9, 'zed', 4.5)")
        out = session.execute("SELECT score FROM users WHERE id = 9")
        assert out.column("score").to_pylist() == [4.5]

    def test_call_compact_and_rollback(self, session):
        session.execute("INSERT INTO users VALUES (1, 'v2', 99, 'sf')")
        out = session.execute("CALL compact('users')")
        assert out.column("compacted_partitions").to_pylist() == [1]
        out = session.execute("CALL rollback('users', 0)")
        assert out.column("rolled_back_partitions").to_pylist() == [1]
        got = session.execute("SELECT name FROM users WHERE id = 1")
        assert got.column("name").to_pylist() == ["alice"]

    def test_call_unknown(self, session):
        with pytest.raises(Exception):
            session.execute("CALL frobnicate('users')")


class TestSchemaEvolutionFilters:
    def test_filter_on_added_column_over_old_files(self, session):
        # no-PK table: filter pushdown applies; old files lack the new column
        session.execute("CREATE TABLE plainlogs (id bigint, msg string)")
        session.execute("INSERT INTO plainlogs VALUES (1, 'a'), (2, 'b')")
        session.execute("ALTER TABLE plainlogs ADD COLUMN sev int")
        session.execute("INSERT INTO plainlogs (id, msg, sev) VALUES (3, 'c', 9)")
        out = session.execute("SELECT id FROM plainlogs WHERE sev > 1")
        assert out.column("id").to_pylist() == [3]
        out2 = session.execute("SELECT id FROM plainlogs WHERE sev IS NULL ORDER BY id")
        assert out2.column("id").to_pylist() == [1, 2]

    def test_unterminated_call_args(self, session):
        with pytest.raises(SqlError, match="end of statement"):
            session.execute("CALL compact(")


class TestUpdateDelete:
    def test_delete_where(self, session):
        out = session.execute("DELETE FROM users WHERE city = 'nyc'")
        assert out.column("deleted").to_pylist() == [2]
        remaining = session.execute("SELECT id FROM users ORDER BY id")
        assert remaining.column("id").to_pylist() == [1, 3]
        # delete is a conflict-checked UpdateCommit: version advanced
        t = session.catalog.table("users")
        head = session.catalog.client.store.get_latest_partition_info(t.info.table_id, "-5")
        assert head.commit_op.value == "UpdateCommit"

    def test_update_where(self, session):
        out = session.execute("UPDATE users SET age = 99, city = 'x' WHERE id IN (1, 2)")
        assert out.column("updated").to_pylist() == [2]
        got = session.execute("SELECT id, age, city FROM users ORDER BY id")
        rows = got.to_pylist()
        assert rows[0]["age"] == 99 and rows[0]["city"] == "x"
        assert rows[1]["age"] == 99 and rows[1]["city"] == "x"
        assert rows[2]["age"] == 35  # untouched

    def test_update_pk_rejected(self, session):
        with pytest.raises(Exception, match="primary-key"):
            session.execute("UPDATE users SET id = 7 WHERE id = 1")

    def test_no_match_is_noop(self, session):
        out = session.execute("DELETE FROM users WHERE id = 12345")
        assert out.column("deleted").to_pylist() == [0]
        t = session.catalog.table("users")
        head = session.catalog.client.store.get_latest_partition_info(t.info.table_id, "-5")
        assert head.commit_op.value != "UpdateCommit"  # nothing rewritten

    def test_where_required(self, session):
        from lakesoul_tpu.sql.parser import SqlError

        with pytest.raises(SqlError):
            session.execute("DELETE FROM users")
        with pytest.raises(SqlError):
            session.execute("UPDATE users SET age = 1")


class TestDmlSemantics:
    def test_null_predicate_rows_survive_delete(self, session):
        session.execute("INSERT INTO users (id, name) VALUES (50, 'nullcity')")
        out = session.execute("DELETE FROM users WHERE city = 'nyc'")
        assert out.column("deleted").to_pylist() == [2]
        ids = session.execute("SELECT id FROM users ORDER BY id").column("id").to_pylist()
        assert 50 in ids  # NULL-predicate row kept (three-valued logic)

    def test_update_partition_column_rejected(self, session):
        session.execute(
            "CREATE TABLE pt (id bigint PRIMARY KEY, v double, day string)"
            " PARTITIONED BY (day)"
        )
        session.execute("INSERT INTO pt VALUES (1, 1.0, 'd1')")
        with pytest.raises(Exception, match="range-partition"):
            session.execute("UPDATE pt SET day = 'd2' WHERE id = 1")

    def test_partition_pruned_dml(self, session):
        session.execute(
            "CREATE TABLE pp2 (id bigint PRIMARY KEY, v double, day string)"
            " PARTITIONED BY (day)"
        )
        session.execute("INSERT INTO pp2 VALUES (1, 1.0, 'd1'), (2, 2.0, 'd2')")
        out = session.execute("DELETE FROM pp2 WHERE day = 'd2' AND v > 0")
        assert out.column("deleted").to_pylist() == [1]
        # d1 partition untouched (no new version)
        t = session.catalog.table("pp2")
        store = session.catalog.client.store
        d1 = store.get_latest_partition_info(t.info.table_id, "day=d1")
        assert d1.version == 0


class TestJoins:
    @pytest.fixture()
    def join_session(self, tmp_warehouse):
        catalog = LakeSoulCatalog(str(tmp_warehouse / "j"))
        s = SqlSession(catalog)
        s.execute("CREATE TABLE orders (oid bigint PRIMARY KEY, uid bigint, amount double)")
        s.execute("CREATE TABLE customers (uid bigint PRIMARY KEY, region string)")
        s.execute("INSERT INTO customers VALUES (1, 'eu'), (2, 'us'), (3, 'apac')")
        s.execute(
            "INSERT INTO orders VALUES (10, 1, 5.0), (11, 1, 7.0), (12, 2, 3.0), (13, 9, 1.0)"
        )
        return s

    def test_inner_join(self, join_session):
        out = join_session.execute(
            "SELECT oid, region FROM orders JOIN customers ON orders.uid = customers.uid"
            " ORDER BY oid"
        )
        assert out.column("oid").to_pylist() == [10, 11, 12]
        assert out.column("region").to_pylist() == ["eu", "eu", "us"]

    def test_left_join_and_where(self, join_session):
        out = join_session.execute(
            "SELECT oid, region FROM orders LEFT JOIN customers ON uid = uid ORDER BY oid"
        )
        assert out.num_rows == 4
        assert out.column("region").to_pylist()[-1] is None  # unmatched uid 9
        out2 = join_session.execute(
            "SELECT oid FROM orders JOIN customers ON uid = uid WHERE region = 'eu' ORDER BY oid"
        )
        assert out2.column("oid").to_pylist() == [10, 11]

    def test_join_with_aggregate(self, join_session):
        out = join_session.execute(
            "SELECT region, sum(amount) AS total FROM orders"
            " JOIN customers ON uid = uid GROUP BY region ORDER BY region"
        )
        assert out.column("region").to_pylist() == ["eu", "us"]
        assert out.column("total").to_pylist() == [12.0, 3.0]


class TestJoinBinding2:
    @pytest.fixture()
    def js(self, tmp_warehouse):
        catalog = LakeSoulCatalog(str(tmp_warehouse / "jb"))
        s = SqlSession(catalog)
        s.execute("CREATE TABLE o2 (oid bigint PRIMARY KEY, customer_id bigint)")
        s.execute("CREATE TABLE c2 (uid bigint PRIMARY KEY, region string)")
        s.execute("INSERT INTO c2 VALUES (1, 'eu')")
        s.execute("INSERT INTO o2 VALUES (10, 1)")
        return s

    def test_on_clause_order_independent(self, js):
        a = js.execute("SELECT oid, region FROM o2 JOIN c2 ON o2.customer_id = c2.uid")
        b = js.execute("SELECT oid, region FROM o2 JOIN c2 ON c2.uid = o2.customer_id")
        assert a.to_pylist() == b.to_pylist() == [{"oid": 10, "region": "eu"}]

    def test_bare_names_bound_by_membership(self, js):
        out = js.execute("SELECT oid, region FROM o2 JOIN c2 ON customer_id = uid")
        assert out.to_pylist() == [{"oid": 10, "region": "eu"}]
        out2 = js.execute("SELECT oid, region FROM o2 JOIN c2 ON uid = customer_id")
        assert out2.to_pylist() == [{"oid": 10, "region": "eu"}]

    def test_base_filter_pushdown_with_join(self, js):
        out = js.execute(
            "SELECT oid FROM o2 JOIN c2 ON customer_id = uid WHERE oid = 10"
        )
        assert out.column("oid").to_pylist() == [10]


class TestJoinEdgeCases:
    @pytest.fixture()
    def js2(self, tmp_warehouse):
        catalog = LakeSoulCatalog(str(tmp_warehouse / "je"))
        s = SqlSession(catalog)
        s.execute("CREATE TABLE o3 (oid bigint PRIMARY KEY, uid bigint, region string)")
        s.execute("CREATE TABLE c3 (uid bigint PRIMARY KEY, region string)")
        s.execute("INSERT INTO c3 VALUES (1, 'eu')")
        s.execute("INSERT INTO o3 VALUES (10, 1, 'order-region')")
        return s

    def test_where_on_right_key_column(self, js2):
        out = js2.execute("SELECT oid FROM o3 JOIN c3 ON o3.uid = c3.uid WHERE uid = 1")
        assert out.column("oid").to_pylist() == [10]

    def test_colliding_non_key_columns_suffixed(self, js2):
        out = js2.execute("SELECT oid, region FROM o3 JOIN c3 ON o3.uid = c3.uid")
        assert out.column("region").to_pylist() == ["order-region"]  # left wins
        full = js2.execute("SELECT * FROM o3 JOIN c3 ON o3.uid = c3.uid")
        assert "region_c3" in full.column_names  # right side suffixed


class TestExpressions:
    def test_select_arithmetic(self, session):
        out = session.execute("SELECT id, age * 2 AS dbl, age + id FROM users WHERE id = 1")
        assert out.column("dbl").to_pylist() == [60]
        assert out.column("age+id").to_pylist() == [31]

    def test_aggregate_over_expression(self, session):
        out = session.execute("SELECT sum(age * 2) AS s, avg(age + 0) AS a FROM users")
        assert out.column("s").to_pylist() == [236]
        assert out.column("a").to_pylist() == [29.5]

    def test_grouped_expression_aggregate(self, session):
        out = session.execute(
            "SELECT city, sum(age * (1 + 0)) AS s FROM users GROUP BY city ORDER BY city"
        )
        assert out.column("s").to_pylist() == [53, 65]

    def test_unary_minus_and_parens(self, session):
        out = session.execute("SELECT (age - 30) * -1 AS neg FROM users WHERE id = 3")
        assert out.column("neg").to_pylist() == [-5]
        out2 = session.execute("SELECT id FROM users WHERE age > -100 AND id = 1")
        assert out2.column("id").to_pylist() == [1]


class TestTpchLite:
    # full Q1-Q22 coverage (with pandas result checks) lives in
    # tests/test_tpch.py; this is a smoke check of the harness surface
    def test_harness_smoke(self, tmp_warehouse):
        from lakesoul_tpu.sql.tpch import QUERIES, TpchLite

        catalog = LakeSoulCatalog(str(tmp_warehouse / "tpch"))
        h = TpchLite(catalog, scale_rows=3000, seed=1)
        h.generate()
        assert len(QUERIES) == 22
        secs, q1 = h.run("q01")
        assert secs >= 0 and q1.num_rows > 0
        assert h.verify("q06")


class TestExpressionEdgeCases:
    def test_literal_only_select(self, session):
        out = session.execute("SELECT 1 AS one FROM users")
        assert out.column("one").to_pylist() == [1, 1, 1, 1]

    def test_aggregate_of_literal(self, session):
        out = session.execute("SELECT sum(2) AS s FROM users")
        assert out.column("s").to_pylist() == [8]  # 4 rows * 2
        g = session.execute("SELECT city, sum(1) AS n FROM users GROUP BY city ORDER BY city")
        assert g.column("n").to_pylist() == [2, 2]

    def test_duplicate_labels_preserved(self, session):
        out = session.execute("SELECT age, age FROM users WHERE id = 1")
        assert out.num_columns == 2

    def test_unary_minus_on_string_rejected(self, session):
        from lakesoul_tpu.sql.parser import SqlError

        with pytest.raises(SqlError, match="numeric"):
            session.execute("SELECT id FROM users WHERE name = -'x'")


class TestSqlSurfaceR2:
    """CASE / HAVING / subqueries / derived tables / DISTINCT / LIKE /
    BETWEEN / substring / expressions over aggregates (VERDICT r1 #3)."""

    def test_case_when(self, session):
        out = session.execute(
            "SELECT id, CASE WHEN age >= 30 THEN 'senior' WHEN age >= 26 THEN 'mid'"
            " ELSE 'junior' END AS band FROM users ORDER BY id"
        )
        assert out.column("band").to_pylist() == ["senior", "junior", "senior", "mid"]

    def test_case_without_else_yields_null(self, session):
        out = session.execute(
            "SELECT id, CASE WHEN age > 100 THEN 1 END AS x FROM users ORDER BY id"
        )
        assert out.column("x").to_pylist() == [None] * 4

    def test_sum_of_case(self, session):
        out = session.execute(
            "SELECT sum(CASE WHEN city = 'sf' THEN age ELSE 0 END) AS sf_age FROM users"
        )
        assert out.column("sf_age").to_pylist() == [65]

    def test_having(self, session):
        session.execute("INSERT INTO users VALUES (9, 'zed', 40, 'sf')")
        out = session.execute(
            "SELECT city, count(*) AS n FROM users GROUP BY city HAVING count(*) > 2"
        )
        assert out.column("city").to_pylist() == ["sf"]
        assert out.column("n").to_pylist() == [3]

    def test_having_on_alias(self, session):
        out = session.execute(
            "SELECT city, avg(age) AS a FROM users GROUP BY city HAVING a > 30"
        )
        assert out.column("city").to_pylist() == ["sf"]

    def test_expression_over_aggregates(self, session):
        out = session.execute(
            "SELECT 100 * sum(age) / count(*) AS avg100 FROM users"
        )
        assert out.column("avg100").to_pylist() == [2950.0]

    def test_scalar_subquery(self, session):
        out = session.execute(
            "SELECT id FROM users WHERE age > (SELECT avg(age) FROM users) ORDER BY id"
        )
        assert out.column("id").to_pylist() == [1, 3]

    def test_in_subquery(self, session):
        out = session.execute(
            "SELECT name FROM users WHERE id IN (SELECT id FROM users WHERE city = 'sf')"
            " ORDER BY id"
        )
        assert out.column("name").to_pylist() == ["alice", "carol"]

    def test_not_in_subquery(self, session):
        out = session.execute(
            "SELECT name FROM users WHERE id NOT IN"
            " (SELECT id FROM users WHERE city = 'sf') ORDER BY id"
        )
        assert out.column("name").to_pylist() == ["bob", "dave"]

    def test_exists(self, session):
        out = session.execute(
            "SELECT count(*) AS n FROM users WHERE EXISTS"
            " (SELECT id FROM users WHERE age > 100)"
        )
        assert out.column("n").to_pylist() == [0]
        out2 = session.execute(
            "SELECT count(*) AS n FROM users WHERE NOT EXISTS"
            " (SELECT id FROM users WHERE age > 100)"
        )
        assert out2.column("n").to_pylist() == [4]

    def test_derived_table(self, session):
        out = session.execute(
            "SELECT city, n FROM (SELECT city, count(*) AS n FROM users GROUP BY city) t"
            " WHERE n >= 2 ORDER BY city"
        )
        assert out.column("city").to_pylist() == ["nyc", "sf"]

    def test_join_derived_table(self, session):
        out = session.execute(
            "SELECT name, n FROM users JOIN"
            " (SELECT city AS jcity, count(*) AS n FROM users GROUP BY city) t"
            " ON city = jcity WHERE age > 28 ORDER BY id"
        )
        assert out.column("name").to_pylist() == ["alice", "carol"]
        assert out.column("n").to_pylist() == [2, 2]

    def test_distinct(self, session):
        out = session.execute("SELECT DISTINCT city FROM users")
        assert sorted(out.column("city").to_pylist()) == ["nyc", "sf"]

    def test_count_distinct(self, session):
        out = session.execute("SELECT count(DISTINCT city) AS c FROM users")
        assert out.column("c").to_pylist() == [2]

    def test_like_and_not_like(self, session):
        out = session.execute("SELECT name FROM users WHERE name LIKE 'a%'")
        assert out.column("name").to_pylist() == ["alice"]
        out2 = session.execute(
            "SELECT name FROM users WHERE name NOT LIKE '%e%' ORDER BY id"
        )
        assert out2.column("name").to_pylist() == ["bob", "carol"]

    def test_between(self, session):
        out = session.execute(
            "SELECT id FROM users WHERE age BETWEEN 26 AND 31 ORDER BY id"
        )
        assert out.column("id").to_pylist() == [1, 4]

    def test_substring(self, session):
        out = session.execute(
            "SELECT substring(name, 1, 2) AS pre FROM users ORDER BY id"
        )
        assert out.column("pre").to_pylist() == ["al", "bo", "ca", "da"]

    def test_order_by_unprojected_column(self, session):
        out = session.execute("SELECT name FROM users ORDER BY age DESC, id")
        assert out.column("name").to_pylist() == ["carol", "alice", "dave", "bob"]
        assert out.num_columns == 1

    def test_column_vs_column_comparison(self, session):
        out = session.execute("SELECT id FROM users WHERE age > id + 25 ORDER BY id")
        assert out.column("id").to_pylist() == [1, 3]

    def test_table_alias_accepted(self, session):
        out = session.execute("SELECT u.id FROM users u WHERE u.age > 30")
        assert out.column("id").to_pylist() == [3]

    def test_case_guards_failing_branch(self, session):
        # SQL guarantees the guarded branch is not evaluated on excluded rows
        session.execute("CREATE TABLE dz (id bigint PRIMARY KEY, a bigint, b bigint)")
        session.execute("INSERT INTO dz VALUES (1, 10, 0), (2, 10, 2), (3, 7, 7)")
        out = session.execute(
            "SELECT id, CASE WHEN b <> 0 THEN a / b ELSE -1 END AS r FROM dz ORDER BY id"
        )
        assert out.column("r").to_pylist() == [-1, 5, 1]

    def test_distinct_only_for_count(self, session):
        with pytest.raises(SqlError, match="DISTINCT"):
            session.execute("SELECT sum(DISTINCT age) FROM users")

    def test_literal_division_matches_runtime(self, session):
        out = session.execute("SELECT 5 / 2 AS lit, id / 2 AS col FROM users WHERE id = 5")
        # both sides integer-divide (pc.divide semantics), consistently
        session.execute("INSERT INTO users (id, name) VALUES (5, 'eve')")
        out = session.execute("SELECT 5 / 2 AS lit, id / 2 AS col FROM users WHERE id = 5")
        assert out.column("lit").to_pylist() == [2]
        assert out.column("col").to_pylist() == [2]
        with pytest.raises(SqlError, match="division by zero"):
            session.execute("SELECT 1 / 0 FROM users")


class TestInsertSelect:
    def test_insert_from_select(self, session):
        session.execute(
            "CREATE TABLE seniors (id bigint PRIMARY KEY, name string)"
        )
        out = session.execute(
            "INSERT INTO seniors SELECT id, name FROM users WHERE age >= 30"
        )
        assert out.column("inserted").to_pylist() == [2]
        got = session.execute("SELECT name FROM seniors ORDER BY id")
        assert got.column("name").to_pylist() == ["alice", "carol"]

    def test_insert_select_with_column_list_and_cast(self, session):
        session.execute("CREATE TABLE agecopy (id bigint PRIMARY KEY, age double)")
        session.execute("INSERT INTO agecopy (id, age) SELECT id, age FROM users")
        got = session.execute("SELECT age FROM agecopy ORDER BY id")
        assert got.column("age").to_pylist() == [30.0, 25.0, 35.0, 28.0]

    def test_arity_mismatch_rejected(self, session):
        session.execute("CREATE TABLE x2 (id bigint PRIMARY KEY, name string)")
        with pytest.raises(SqlError, match="column list"):
            session.execute("INSERT INTO x2 (id) SELECT id, name FROM users")


class TestConsoleManagement:
    def test_assets_clean_cache_commands(self, tmp_warehouse):
        from lakesoul_tpu.service.console import Console

        c = Console(LakeSoulCatalog(str(tmp_warehouse)))
        c.execute("CREATE TABLE m (id bigint, v double)")
        c.execute("INSERT INTO m VALUES (1, 1.0)")
        assets = c.execute("assets")
        assert "m" in assets and "live_files" in assets
        cleaned = c.execute("clean")
        assert "versions_dropped=" in cleaned
        stats = c.execute("cache-stats")
        assert "hits=" in stats


class TestCtesAndSetOps:
    """WITH (CTEs, inlined as derived tables) + UNION/INTERSECT/EXCEPT."""

    def test_union_all_and_distinct(self, session):
        out = session.execute(
            "SELECT city FROM users WHERE age > 29"
            " UNION ALL SELECT city FROM users WHERE city = 'sf'"
        )
        assert sorted(out.column("city").to_pylist()) == ["sf", "sf", "sf", "sf"]
        out = session.execute(
            "SELECT city FROM users WHERE age > 29"
            " UNION SELECT city FROM users WHERE city = 'sf'"
        )
        assert out.column("city").to_pylist() == ["sf"]

    def test_union_order_limit_bind_to_whole(self, session):
        out = session.execute(
            "SELECT id FROM users WHERE id <= 2"
            " UNION ALL SELECT id FROM users WHERE id >= 3"
            " ORDER BY id DESC LIMIT 3"
        )
        assert out.column("id").to_pylist() == [4, 3, 2]

    def test_union_type_promotion_and_rename(self, session):
        out = session.execute(
            "SELECT id, age FROM users WHERE id = 1"
            " UNION ALL SELECT id, 99.5 FROM users WHERE id = 2"
        )
        got = sorted(out.to_pylist(), key=lambda r: r["id"])
        assert got[0]["age"] == 30.0 and got[1]["age"] == 99.5

    def test_intersect_and_except(self, session):
        out = session.execute(
            "SELECT city FROM users INTERSECT SELECT city FROM users WHERE age < 29"
        )
        assert sorted(out.column("city").to_pylist()) == ["nyc"]
        out = session.execute(
            "SELECT city FROM users EXCEPT SELECT city FROM users WHERE age < 29"
        )
        assert out.column("city").to_pylist() == ["sf"]

    def test_cte_basic_and_chained(self, session):
        out = session.execute(
            "WITH sf AS (SELECT id, age FROM users WHERE city = 'sf'),"
            " old_sf AS (SELECT id FROM sf WHERE age > 31)"
            " SELECT id FROM old_sf"
        )
        assert out.column("id").to_pylist() == [3]

    def test_cte_in_join_and_subquery(self, session):
        out = session.execute(
            "WITH sf AS (SELECT id, city FROM users WHERE city = 'sf')"
            " SELECT u.id FROM users u INNER JOIN sf ON u.id = sf.id ORDER BY u.id"
        )
        assert out.column("id").to_pylist() == [1, 3]
        out = session.execute(
            "WITH young AS (SELECT id FROM users WHERE age < 29)"
            " SELECT name FROM users WHERE id IN (SELECT id FROM young) ORDER BY name"
        )
        assert out.column("name").to_pylist() == ["bob", "dave"]

    def test_cte_aggregate_body_and_union_body(self, session):
        out = session.execute(
            "WITH per_city AS ("
            "   SELECT city, count(*) AS n FROM users GROUP BY city"
            " ) SELECT city FROM per_city WHERE n = 2 ORDER BY city"
        )
        assert out.column("city").to_pylist() == ["nyc", "sf"]
        out = session.execute(
            "WITH both_ends AS ("
            "   SELECT id FROM users WHERE id = 1 UNION ALL"
            "   SELECT id FROM users WHERE id = 4"
            " ) SELECT count(*) AS n FROM both_ends"
        )
        assert out.column("n").to_pylist() == [2]

    def test_insert_from_union_select(self, session):
        session.execute(
            "CREATE TABLE ids (id bigint PRIMARY KEY) WITH (hashBucketNum = '1')"
        )
        session.execute(
            "INSERT INTO ids SELECT id FROM users WHERE id = 1"
            " UNION ALL SELECT id FROM users WHERE id = 2"
        )
        out = session.execute("SELECT id FROM ids ORDER BY id")
        assert out.column("id").to_pylist() == [1, 2]

    def test_intersect_binds_tighter_than_union(self, session):
        """Standard SQL precedence: a UNION (b INTERSECT c), not
        (a UNION b) INTERSECT c."""
        stmt = parse("SELECT x FROM a UNION SELECT x FROM b INTERSECT SELECT x FROM c")
        assert stmt.op == "union"
        assert stmt.right.op == "intersect"
        # semantic check: sf rows survive even though absent from the
        # INTERSECT operands
        out = session.execute(
            "SELECT city FROM users WHERE city = 'sf'"
            " UNION SELECT city FROM users WHERE age < 29"
            " INTERSECT SELECT city FROM users WHERE age = 25"
        )
        assert sorted(out.column("city").to_pylist()) == ["nyc", "sf"]

    def test_set_op_arity_mismatch(self, session):
        with pytest.raises(SqlError, match="arity"):
            session.execute("SELECT id, age FROM users UNION SELECT id FROM users")


class TestWindowFunctions:
    """OVER (PARTITION BY ... ORDER BY ...): ranks, offsets, running and
    whole-partition aggregates (DataFusion window-planner role)."""

    @pytest.fixture()
    def wsession(self, tmp_warehouse):
        catalog = LakeSoulCatalog(str(tmp_warehouse))
        s = SqlSession(catalog)
        s.execute(
            "CREATE TABLE sales (id bigint PRIMARY KEY, region string,"
            " amt double, day int) WITH (hashBucketNum = '1')"
        )
        s.execute(
            "INSERT INTO sales VALUES"
            " (1, 'w', 10.0, 1), (2, 'w', 30.0, 2), (3, 'w', 20.0, 2),"
            " (4, 'e', 5.0, 1), (5, 'e', 50.0, 3), (6, 'e', 50.0, 2)"
        )
        return s

    def _by_id(self, out, col):
        rows = sorted(out.to_pylist(), key=lambda r: r["id"])
        return [r[col] for r in rows]

    def test_row_number(self, wsession):
        out = wsession.execute(
            "SELECT id, row_number() OVER (PARTITION BY region ORDER BY amt) AS rn"
            " FROM sales"
        )
        assert self._by_id(out, "rn") == [1, 3, 2, 1, 2, 3]

    def test_rank_and_dense_rank_with_ties(self, wsession):
        out = wsession.execute(
            "SELECT id, rank() OVER (PARTITION BY region ORDER BY amt DESC) AS r,"
            " dense_rank() OVER (PARTITION BY region ORDER BY amt DESC) AS dr"
            " FROM sales"
        )
        # east: amts 5, 50, 50 → desc ranks: 50→1, 50→1, 5→3 (dense: 2)
        assert self._by_id(out, "r") == [3, 1, 2, 3, 1, 1]
        assert self._by_id(out, "dr") == [3, 1, 2, 2, 1, 1]

    def test_running_sum_range_peers(self, wsession):
        out = wsession.execute(
            "SELECT id, sum(amt) OVER (PARTITION BY region ORDER BY day) AS s"
            " FROM sales"
        )
        # west day2 has two rows (ids 2,3): RANGE peers share 10+30+20=60
        assert self._by_id(out, "s") == [10.0, 60.0, 60.0, 5.0, 105.0, 55.0]

    def test_partition_aggregate_broadcast(self, wsession):
        out = wsession.execute(
            "SELECT id, sum(amt) OVER (PARTITION BY region) AS tot,"
            " count(*) OVER (PARTITION BY region) AS n FROM sales"
        )
        assert self._by_id(out, "tot") == [60.0, 60.0, 60.0, 105.0, 105.0, 105.0]
        assert self._by_id(out, "n") == [3, 3, 3, 3, 3, 3]

    def test_lag_lead(self, wsession):
        out = wsession.execute(
            "SELECT id, lag(amt) OVER (PARTITION BY region ORDER BY day, id) AS prev,"
            " lead(amt, 1, -1.0) OVER (PARTITION BY region ORDER BY day, id) AS nxt"
            " FROM sales"
        )
        assert self._by_id(out, "prev") == [None, 10.0, 30.0, None, 50.0, 5.0]
        assert self._by_id(out, "nxt") == [30.0, 20.0, -1.0, 50.0, -1.0, 50.0]

    def test_window_in_expression_and_global(self, wsession):
        out = wsession.execute(
            "SELECT id, amt * 100 / sum(amt) OVER (PARTITION BY region) AS pct,"
            " row_number() OVER (ORDER BY amt DESC, id) AS g FROM sales"
        )
        pct = self._by_id(out, "pct")
        assert abs(pct[0] - 10.0 / 60.0 * 100) < 1e-9
        # amt desc, id asc: id5(50), id6(50), id2(30), id3(20), id1(10), id4(5)
        assert self._by_id(out, "g") == [5, 3, 4, 6, 1, 2]

    def test_window_over_derived_and_cte(self, wsession):
        out = wsession.execute(
            "WITH w AS (SELECT region, amt FROM sales WHERE amt > 5)"
            " SELECT region, rank() OVER (PARTITION BY region ORDER BY amt) AS r"
            " FROM w ORDER BY region, r"
        )
        # east keeps the tied 50s (both rank 1); west keeps 10, 20, 30
        assert out.column("r").to_pylist() == [1, 1, 1, 2, 3]

    def test_running_avg_and_min_max(self, wsession):
        out = wsession.execute(
            "SELECT id, avg(amt) OVER (PARTITION BY region ORDER BY day, id) AS a,"
            " max(amt) OVER (PARTITION BY region ORDER BY day, id) AS m FROM sales"
        )
        assert self._by_id(out, "a") == [10.0, 20.0, 20.0, 5.0, 35.0, 27.5]
        assert self._by_id(out, "m") == [10.0, 30.0, 30.0, 5.0, 50.0, 50.0]

    def test_null_skipping_in_window_aggregates(self, wsession):
        """SQL frame semantics: NULLs are skipped — running values carry
        forward through them, and an all-NULL frame sums to NULL, not 0."""
        wsession.execute(
            "CREATE TABLE nw (id bigint PRIMARY KEY, grp string, x double, d int)"
            " WITH (hashBucketNum = '1')"
        )
        wsession.execute(
            "INSERT INTO nw (id, grp, x, d) VALUES"
            " (1, 'a', 10.0, 1), (2, 'a', NULL, 2), (3, 'a', 20.0, 3),"
            " (4, 'b', NULL, 1), (5, 'b', NULL, 2)"
        )
        out = wsession.execute(
            "SELECT id, sum(x) OVER (PARTITION BY grp ORDER BY d) AS s,"
            " avg(x) OVER (PARTITION BY grp ORDER BY d) AS a,"
            " min(x) OVER (PARTITION BY grp ORDER BY d) AS m,"
            " sum(x) OVER (PARTITION BY grp) AS tot FROM nw"
        )
        rows = sorted(out.to_pylist(), key=lambda r: r["id"])
        assert [r["s"] for r in rows] == [10.0, 10.0, 30.0, None, None]
        assert [r["a"] for r in rows] == [10.0, 10.0, 15.0, None, None]
        assert [r["m"] for r in rows] == [10.0, 10.0, 10.0, None, None]
        assert [r["tot"] for r in rows] == [30.0, 30.0, 30.0, None, None]

    def test_window_requires_order(self, wsession):
        with pytest.raises(SqlError, match="requires ORDER BY"):
            wsession.execute("SELECT rank() OVER (PARTITION BY region) FROM sales")


class TestGroupingSets:
    """ROLLUP / CUBE / GROUPING SETS expansion (the DataFusion planner role);
    subtotal rows surface missing grouping columns as NULL."""

    @pytest.fixture()
    def gsession(self, tmp_warehouse):
        catalog = LakeSoulCatalog(str(tmp_warehouse))
        s = SqlSession(catalog)
        s.execute(
            "CREATE TABLE g (id bigint PRIMARY KEY, r string, c string, v bigint)"
            " WITH (hashBucketNum = '1')"
        )
        s.execute(
            "INSERT INTO g VALUES (1,'a','x',1), (2,'a','y',2), (3,'b','x',4), (4,'b','y',8)"
        )
        return s

    def test_rollup(self, gsession):
        out = gsession.execute(
            "SELECT r, c, sum(v) AS s FROM g GROUP BY ROLLUP(r, c)"
        )
        rows = {(x["r"], x["c"]): x["s"] for x in out.to_pylist()}
        assert rows == {
            ("a", "x"): 1, ("a", "y"): 2, ("b", "x"): 4, ("b", "y"): 8,
            ("a", None): 3, ("b", None): 12, (None, None): 15,
        }

    def test_cube(self, gsession):
        out = gsession.execute("SELECT r, c, sum(v) AS s FROM g GROUP BY CUBE(r, c)")
        rows = {(x["r"], x["c"]): x["s"] for x in out.to_pylist()}
        # rollup rows plus the (None, c) slices
        assert rows[(None, "x")] == 5 and rows[(None, "y")] == 10
        assert rows[(None, None)] == 15 and len(rows) == 9

    def test_grouping_sets_explicit(self, gsession):
        out = gsession.execute(
            "SELECT r, c, sum(v) AS s FROM g GROUP BY GROUPING SETS ((r), (c), ())"
        )
        rows = {(x["r"], x["c"]): x["s"] for x in out.to_pylist()}
        assert rows == {
            ("a", None): 3, ("b", None): 12,
            (None, "x"): 5, (None, "y"): 10, (None, None): 15,
        }

    def test_rollup_with_having_and_count(self, gsession):
        out = gsession.execute(
            "SELECT r, c, count(*) AS n FROM g GROUP BY ROLLUP(r, c) HAVING n > 1"
        )
        rows = {(x["r"], x["c"]): x["n"] for x in out.to_pylist()}
        assert rows == {("a", None): 2, ("b", None): 2, (None, None): 4}

    def test_plain_group_by_columns_named_rollup(self, gsession):
        """rollup/cube/grouping stay usable as plain identifiers."""
        gsession.execute(
            "CREATE TABLE rb (id bigint PRIMARY KEY, rollup string, v bigint)"
            " WITH (hashBucketNum = '1')"
        )
        gsession.execute("INSERT INTO rb VALUES (1, 'p', 2), (2, 'p', 3), (3, 'q', 5)")
        out = gsession.execute("SELECT rollup, sum(v) AS s FROM rb GROUP BY rollup")
        rows = {x["rollup"]: x["s"] for x in out.to_pylist()}
        assert rows == {"p": 5, "q": 5}


class TestTemporalLiterals:
    @pytest.fixture()
    def tsession(self, tmp_warehouse):
        import datetime

        import numpy as np

        catalog = LakeSoulCatalog(str(tmp_warehouse))
        t = catalog.create_table(
            "ev",
            pa.schema([("id", pa.int64()), ("ts", pa.timestamp("us")), ("d", pa.date32())]),
            primary_keys=["id"],
        )
        base = datetime.datetime(2026, 7, 1)
        t.write_arrow(
            pa.table(
                {
                    "id": np.arange(48),
                    "ts": pa.array([base + datetime.timedelta(hours=i) for i in range(48)]),
                    "d": pa.array(
                        [(base + datetime.timedelta(hours=i)).date() for i in range(48)]
                    ),
                }
            )
        )
        return SqlSession(catalog)

    def test_timestamp_literal_compare(self, tsession):
        out = tsession.execute(
            "SELECT count(*) AS c FROM ev WHERE ts >= TIMESTAMP '2026-07-02 00:00:00'"
        )
        assert out.column("c").to_pylist() == [24]

    def test_date_literal_equality(self, tsession):
        out = tsession.execute("SELECT count(*) AS c FROM ev WHERE d = DATE '2026-07-02'")
        assert out.column("c").to_pylist() == [24]

    def test_timestamp_between(self, tsession):
        out = tsession.execute(
            "SELECT count(*) AS c FROM ev WHERE ts BETWEEN"
            " TIMESTAMP '2026-07-01 05:00:00' AND TIMESTAMP '2026-07-01 10:00:00'"
        )
        assert out.column("c").to_pylist() == [6]

    def test_bad_literal_raises(self, tsession):
        with pytest.raises(SqlError, match="TIMESTAMP literal"):
            tsession.execute("SELECT count(*) FROM ev WHERE ts > TIMESTAMP 'not-a-time'")


class TestTimeTravelSql:
    @pytest.fixture()
    def ttsession(self, tmp_warehouse):
        import time

        catalog = LakeSoulCatalog(str(tmp_warehouse))
        t = catalog.create_table(
            "tt", pa.schema([("id", pa.int64()), ("v", pa.int64())]), primary_keys=["id"]
        )
        t.write_arrow(pa.table({"id": np.arange(10), "v": np.zeros(10, np.int64)}))
        time.sleep(0.02)
        mid = int(time.time() * 1000)
        time.sleep(0.02)
        t.write_arrow(pa.table({"id": np.arange(10, 20), "v": np.ones(10, np.int64)}))
        return SqlSession(catalog), mid

    def test_spark_style_timestamp_as_of(self, ttsession):
        import datetime

        s, mid = ttsession
        # aware UTC literal: naive AS OF strings are interpreted as UTC (not
        # host-local), pinned separately in test_advice_r2.py
        iso = datetime.datetime.fromtimestamp(
            mid / 1000, tz=datetime.timezone.utc
        ).isoformat()
        out = s.execute(f"SELECT count(*) AS c FROM tt TIMESTAMP AS OF '{iso}'")
        assert out.column("c").to_pylist() == [10]

    def test_system_time_as_of_epoch_ms(self, ttsession):
        s, mid = ttsession
        out = s.execute(f"SELECT sum(v) AS sv FROM tt FOR SYSTEM_TIME AS OF {mid}")
        assert out.column("sv").to_pylist() == [0]
        # latest still sees both writes
        out = s.execute("SELECT sum(v) AS sv FROM tt")
        assert out.column("sv").to_pylist() == [10]

    def test_as_of_with_where_and_alias(self, ttsession):
        s, mid = ttsession
        out = s.execute(
            f"SELECT count(*) AS c FROM tt FOR SYSTEM_TIME AS OF {mid} x WHERE x.id >= 5"
        )
        assert out.column("c").to_pylist() == [5]

    def test_bad_as_of_raises(self, ttsession):
        s, _ = ttsession
        with pytest.raises(SqlError, match="AS OF"):
            s.execute("SELECT * FROM tt TIMESTAMP AS OF 'nope'")
        with pytest.raises(SqlError, match="AS OF"):
            s.execute("SELECT * FROM tt FOR SYSTEM_TIME AS OF id")


class TestExplain:
    @pytest.fixture()
    def esession(self, tmp_warehouse):
        catalog = LakeSoulCatalog(str(tmp_warehouse))
        s = SqlSession(catalog)
        s.execute(
            "CREATE TABLE ord (id bigint PRIMARY KEY, region string, amt double)"
            " WITH (hashBucketNum = '4')"
        )
        s.execute(
            "INSERT INTO ord VALUES (1,'e',10.0), (2,'w',20.0), (3,'e',30.0), (4,'w',40.0)"
        )
        return s

    def test_explain_runs_nothing_and_shows_plan(self, esession):
        out = esession.execute(
            "EXPLAIN SELECT region, sum(amt) AS s FROM ord WHERE amt > 0"
            " GROUP BY ROLLUP(region) ORDER BY s LIMIT 5"
        )
        plan = "\n".join(out.column("plan").to_pylist())
        assert "Scan: table=ord" in plan
        assert '"op": "gt"' in plan  # pushdown shown
        assert "Aggregate: group_by=['region'] sets=2" in plan
        assert "Sort:" in plan and "Limit: 5" in plan

    def test_explain_shows_bucket_pruning(self, esession):
        out = esession.execute("EXPLAIN SELECT amt FROM ord WHERE id = 3 AND amt > 0")
        plan = "\n".join(out.column("plan").to_pylist())
        assert "units=1" in plan and "unit-pruned 2 of 3" in plan  # 4 rows land in 3 buckets

    def test_explain_mirrors_count_shortcut_and_bare_aggregates(self, esession):
        out = esession.execute("EXPLAIN SELECT count(*) FROM ord")
        plan = "\n".join(out.column("plan").to_pylist())
        assert "MetadataCount" in plan and "Scan" not in plan
        out = esession.execute("EXPLAIN SELECT sum(amt) FROM ord")
        plan = "\n".join(out.column("plan").to_pylist())
        assert "Aggregate" in plan  # bare aggregate still reduces

    def test_explain_early_stop_limit(self, esession):
        out = esession.execute("EXPLAIN SELECT * FROM ord LIMIT 2")
        plan = "\n".join(out.column("plan").to_pylist())
        assert "early-stop limit: 2" in plan

    def test_explain_setop_and_derived(self, esession):
        out = esession.execute(
            "EXPLAIN SELECT id FROM ord WHERE region = 'e'"
            " UNION SELECT id FROM ord WHERE region = 'w'"
        )
        plan = "\n".join(out.column("plan").to_pylist())
        assert "SetOp: union" in plan and plan.count("Scan: table=ord") == 2
        out = esession.execute(
            "EXPLAIN SELECT t.r FROM (SELECT region AS r FROM ord) t WHERE t.r = 'e'"
        )
        plan = "\n".join(out.column("plan").to_pylist())
        assert "DerivedTable" in plan


class TestAndConjunctBucketPruning:
    def test_point_lookup_with_extra_predicates_prunes(self, tmp_warehouse):
        """id = K AND <anything> prunes to one bucket and stays correct."""
        catalog = LakeSoulCatalog(str(tmp_warehouse))
        t = catalog.create_table(
            "pt", pa.schema([("id", pa.int64()), ("v", pa.float64())]),
            primary_keys=["id"], hash_bucket_num=8,
        )
        t.write_arrow(pa.table({"id": np.arange(800), "v": np.arange(800, dtype=np.float64)}))
        from lakesoul_tpu.io.filters import col, extract_pk_equalities

        f = (col("v") > -1.0) & (col("id") == 123)
        assert extract_pk_equalities(f, ["id"]) == [("id", 123)]
        scan = t.scan().filter(f)
        assert scan.explain()["units"] == 1
        out = scan.to_arrow()
        assert out.column("id").to_pylist() == [123]
        # OR across non-PK disables pruning (not provably narrowing)
        g = (col("id") == 123) | (col("v") > 1.0)
        assert extract_pk_equalities(g, ["id"]) == []
        # IN-list inside AND prunes; results complete
        h = col("id").is_in([5, 600]) & (col("v") >= 0)
        rows = t.scan().filter(h).to_arrow().column("id").to_pylist()
        assert sorted(rows) == [5, 600]


class TestOuterJoins:
    """RIGHT / FULL OUTER JOIN (r5): the reference's embedded DataFusion
    serves all join types; the dialect now covers the OUTER family (LEFT
    OUTER already existed as LEFT)."""

    @pytest.fixture()
    def jsession(self, tmp_warehouse):
        cat = LakeSoulCatalog(str(tmp_warehouse))
        s = SqlSession(cat)
        s.execute("CREATE TABLE a (k bigint, x string)")
        s.execute("CREATE TABLE b (k bigint, y double)")
        s.execute("INSERT INTO a VALUES (1,'one'), (2,'two'), (3,'three')")
        s.execute("INSERT INTO b VALUES (2, 2.5), (3, 3.5), (4, 4.5)")
        return s

    def test_right_join(self, jsession):
        out = jsession.execute(
            "SELECT a.k, x, y FROM a RIGHT JOIN b ON a.k = b.k ORDER BY y"
        )
        assert out.column("y").to_pylist() == [2.5, 3.5, 4.5]
        assert out.column("x").to_pylist() == ["two", "three", None]

    def test_right_outer_spelling(self, jsession):
        out = jsession.execute(
            "SELECT x FROM a RIGHT OUTER JOIN b ON a.k = b.k"
        )
        assert sorted(v or "" for v in out.column("x").to_pylist()) \
            == ["", "three", "two"]

    def test_full_outer_join(self, jsession):
        # a.k is NULL on the right-only row — ON keeps BOTH key columns,
        # unlike USING (no silent key coalescing)
        out = jsession.execute(
            "SELECT a.k, x, y FROM a FULL OUTER JOIN b ON a.k = b.k"
        )
        rows = sorted(
            zip(out.column("k").to_pylist(), out.column("x").to_pylist(),
                out.column("y").to_pylist()),
            key=lambda r: (r[0] is None, r[0]),
        )
        assert rows == [
            (1, "one", None), (2, "two", 2.5), (3, "three", 3.5),
            (None, None, 4.5),
        ]

    def test_right_join_key_null_extension(self, jsession):
        out = jsession.execute(
            "SELECT a.k FROM a RIGHT JOIN b ON a.k = b.k ORDER BY y"
        )
        assert out.column("k").to_pylist() == [2, 3, None]
        # and the right-side key is reachable by ITS qualifier
        out = jsession.execute(
            "SELECT b.k AS bk FROM a RIGHT JOIN b ON a.k = b.k ORDER BY y"
        )
        assert out.column("bk").to_pylist() == [2, 3, 4]

    def test_key_anti_join_on_full_outer(self, jsession):
        out = jsession.execute(
            "SELECT y FROM a FULL OUTER JOIN b ON a.k = b.k WHERE a.k IS NULL"
        )
        assert out.column("y").to_pylist() == [4.5]
        out = jsession.execute(
            "SELECT x FROM a FULL OUTER JOIN b ON a.k = b.k WHERE b.k IS NULL"
        )
        assert out.column("x").to_pylist() == ["one"]

    def test_key_anti_join_distinct_names(self, tmp_warehouse):
        cat = LakeSoulCatalog(str(tmp_warehouse))
        s = SqlSession(cat)
        s.execute("CREATE TABLE l (k bigint)")
        s.execute("CREATE TABLE r (kk bigint, z double)")
        s.execute("INSERT INTO l VALUES (1), (2)")
        s.execute("INSERT INTO r VALUES (2, 2.5), (9, 9.5)")
        out = s.execute(
            "SELECT z FROM l FULL OUTER JOIN r ON l.k = r.kk WHERE k IS NULL"
        )
        assert out.column("z").to_pylist() == [9.5]
        out = s.execute(
            "SELECT k FROM l FULL OUTER JOIN r ON l.k = r.kk WHERE kk IS NULL"
        )
        assert out.column("k").to_pylist() == [1]

    def test_left_outer_spelling(self, jsession):
        out = jsession.execute(
            "SELECT k FROM a LEFT OUTER JOIN b ON a.k = b.k WHERE y IS NULL"
        )
        assert out.column("k").to_pylist() == [1]

    def test_anti_join_pattern(self, jsession):
        # the classic NOT-matched pattern over a full outer join
        out = jsession.execute(
            "SELECT y FROM a FULL OUTER JOIN b ON a.k = b.k WHERE x IS NULL"
        )
        assert out.column("y").to_pylist() == [4.5]


class TestScalarFunctions:
    """COALESCE / NULLIF / ABS / ROUND / UPPER / LOWER / LENGTH (r5)."""

    @pytest.fixture()
    def fsession(self, tmp_warehouse):
        cat = LakeSoulCatalog(str(tmp_warehouse))
        s = SqlSession(cat)
        s.execute("CREATE TABLE t (k bigint, x string, v double)")
        s.execute(
            "INSERT INTO t VALUES (1,'one',1.25), (2,'two',-2.5), (3,NULL,NULL)"
        )
        return s

    def test_coalesce(self, fsession):
        out = fsession.execute("SELECT coalesce(x, 'none') AS c FROM t")
        assert out.column("c").to_pylist() == ["one", "two", "none"]
        out = fsession.execute("SELECT coalesce(v, 0.0) AS c FROM t")
        assert out.column("c").to_pylist() == [1.25, -2.5, 0.0]

    def test_nullif(self, fsession):
        out = fsession.execute("SELECT nullif(k, 2) AS n FROM t")
        assert out.column("n").to_pylist() == [1, None, 3]

    def test_abs_round(self, fsession):
        out = fsession.execute("SELECT abs(v) AS a, round(v) AS r FROM t")
        assert out.column("a").to_pylist() == [1.25, 2.5, None]
        # SQL rounds half AWAY from zero (not banker's)
        assert out.column("r").to_pylist() == [1.0, -3.0, None]
        out = fsession.execute("SELECT round(v, 1) AS r FROM t WHERE k = 1")
        assert out.column("r").to_pylist() == [1.3]

    def test_string_functions(self, fsession):
        out = fsession.execute(
            "SELECT upper(x) AS u, lower(upper(x)) AS l, length(x) AS n FROM t"
        )
        assert out.column("u").to_pylist() == ["ONE", "TWO", None]
        assert out.column("l").to_pylist() == ["one", "two", None]
        assert out.column("n").to_pylist() == [3, 3, None]

    def test_functions_in_where_and_aggregates(self, fsession):
        out = fsession.execute(
            "SELECT count(*) AS c FROM t WHERE coalesce(x, 'none') = 'none'"
        )
        assert out.column("c").to_pylist() == [1]
        out = fsession.execute("SELECT sum(abs(v)) AS s FROM t")
        assert out.column("s").to_pylist() == [3.75]

    def test_function_names_still_valid_columns(self, tmp_warehouse):
        # idents, not keywords: a column named `length` keeps working
        cat = LakeSoulCatalog(str(tmp_warehouse))
        s = SqlSession(cat)
        s.execute("CREATE TABLE m (length bigint)")
        s.execute("INSERT INTO m VALUES (7)")
        assert s.execute("SELECT length FROM m").column("length").to_pylist() == [7]

    def test_arity_errors(self, fsession):
        with pytest.raises(SqlError, match="two arguments"):
            fsession.execute("SELECT nullif(k) FROM t")
        with pytest.raises(SqlError, match="one argument"):
            fsession.execute("SELECT abs(k, 2) FROM t")

    def test_later_join_on_suffixed_key_either_operand_order(self, tmp_warehouse):
        """A later ON may reference the suffixed right-join key as either
        operand; both spellings must bind to the surviving right column."""
        cat = LakeSoulCatalog(str(tmp_warehouse))
        s = SqlSession(cat)
        s.execute("CREATE TABLE a (k bigint)")
        s.execute("CREATE TABLE b (k bigint, y double)")
        s.execute("CREATE TABLE c (z bigint, w string)")
        s.execute("INSERT INTO a VALUES (1), (2)")
        s.execute("INSERT INTO b VALUES (2, 2.5), (4, 4.5)")
        s.execute("INSERT INTO c VALUES (2, 'C2'), (4, 'C4')")
        for on in ("c.z = b.k", "b.k = c.z"):
            out = s.execute(
                f"SELECT w FROM a RIGHT JOIN b ON a.k = b.k JOIN c ON {on}"
            )
            assert sorted(out.column("w").to_pylist()) == ["C2", "C4"]

    def test_subquery_rebinding_qualifier_untouched(self, tmp_warehouse):
        """A subquery whose own FROM binds the joined table's name re-scopes
        the qualifier: its inner references must not be renamed."""
        cat = LakeSoulCatalog(str(tmp_warehouse))
        s = SqlSession(cat)
        s.execute("CREATE TABLE a (k bigint)")
        s.execute("CREATE TABLE b (k bigint, y double)")
        s.execute("INSERT INTO a VALUES (1), (2)")
        s.execute("INSERT INTO b VALUES (2, 2.5), (4, 4.5)")
        out = s.execute(
            "SELECT (SELECT max(y) FROM b WHERE b.k = 2) AS m"
            " FROM a RIGHT JOIN b ON a.k = b.k ORDER BY y"
        )
        assert out.column("m").to_pylist() == [2.5, 2.5]


class TestCastAndOffset:
    """CAST(expr AS type) and LIMIT/OFFSET (r5) — the surface ADBC/BI
    clients emit unprompted."""

    @pytest.fixture()
    def csession(self, tmp_warehouse):
        cat = LakeSoulCatalog(str(tmp_warehouse))
        s = SqlSession(cat)
        s.execute("CREATE TABLE t (k bigint, x string, v double)")
        s.execute(
            "INSERT INTO t VALUES (1,'10',1.9), (2,'20',2.1), (3,'30',3.5),"
            " (4,'40',4.4), (5,'50',5.0)"
        )
        return s

    def test_cast_string_to_int(self, csession):
        out = csession.execute("SELECT cast(x AS bigint) AS n FROM t ORDER BY n")
        assert out.column("n").to_pylist() == [10, 20, 30, 40, 50]
        assert out.column("n").type == pa.int64()

    def test_cast_double_to_int_and_back(self, csession):
        out = csession.execute("SELECT cast(k AS double) AS d FROM t WHERE k = 1")
        assert out.column("d").to_pylist() == [1.0]
        assert out.column("d").type == pa.float64()
        out = csession.execute("SELECT cast(k AS string) AS s FROM t WHERE k = 2")
        assert out.column("s").to_pylist() == ["2"]

    def test_cast_in_where_and_aggregate(self, csession):
        out = csession.execute(
            "SELECT sum(cast(x AS bigint)) AS s FROM t WHERE cast(x AS bigint) > 20"
        )
        assert out.column("s").to_pylist() == [120]

    def test_cast_unknown_type(self, csession):
        with pytest.raises(SqlError, match="unknown type"):
            csession.execute("SELECT cast(k AS blob) FROM t")

    def test_cast_still_valid_column_name(self, tmp_warehouse):
        cat = LakeSoulCatalog(str(tmp_warehouse))
        s = SqlSession(cat)
        s.execute("CREATE TABLE m (cast bigint)")
        s.execute("INSERT INTO m VALUES (7)")
        assert s.execute("SELECT cast FROM m").column("cast").to_pylist() == [7]

    def test_limit_offset(self, csession):
        out = csession.execute("SELECT k FROM t ORDER BY k LIMIT 2 OFFSET 1")
        assert out.column("k").to_pylist() == [2, 3]
        out = csession.execute("SELECT k FROM t ORDER BY k OFFSET 3")
        assert out.column("k").to_pylist() == [4, 5]
        out = csession.execute("SELECT k FROM t ORDER BY k LIMIT 10 OFFSET 10")
        assert out.column("k").to_pylist() == []

    def test_offset_on_aggregate_and_count_shortcut(self, csession):
        # count(*) is normally a metadata shortcut; OFFSET must still apply
        out = csession.execute("SELECT count(*) AS c FROM t OFFSET 1")
        assert out.num_rows == 0
        out = csession.execute("SELECT count(*) AS c FROM t")
        assert out.column("c").to_pylist() == [5]

    def test_offset_on_set_op_chain(self, csession):
        out = csession.execute(
            "SELECT k FROM t WHERE k <= 2 UNION ALL SELECT k FROM t WHERE k >= 4"
            " ORDER BY k LIMIT 2 OFFSET 1"
        )
        assert out.column("k").to_pylist() == [2, 4]

    def test_offset_still_valid_column_name(self, tmp_warehouse):
        cat = LakeSoulCatalog(str(tmp_warehouse))
        s = SqlSession(cat)
        s.execute("CREATE TABLE m (offset bigint)")
        s.execute("INSERT INTO m VALUES (3)")
        assert s.execute("SELECT offset FROM m").column("offset").to_pylist() == [3]

    def test_offset_after_derived_table(self, csession):
        out = csession.execute(
            "SELECT k FROM (SELECT k FROM t ORDER BY k) OFFSET 3"
        )
        assert out.column("k").to_pylist() == [4, 5]

    def test_cast_float_to_int_truncates(self, csession):
        # standard SQL / Spark / DuckDB truncate; safe-mode erroring would
        # break every BI client that rounds through integers
        out = csession.execute("SELECT cast(v AS bigint) AS n FROM t ORDER BY k")
        assert out.column("n").to_pylist() == [1, 2, 3, 4, 5]

    def test_cast_parameterized_types(self, csession):
        out = csession.execute("SELECT cast(k AS varchar(10)) AS s FROM t WHERE k = 1")
        assert out.column("s").to_pylist() == ["1"]
        out = csession.execute("SELECT cast(v AS decimal(10, 2)) AS d FROM t WHERE k = 1")
        assert out.column("d").type == pa.decimal128(10, 2)
        assert str(out.column("d").to_pylist()[0]) == "1.90"

    def test_explain_shows_offset(self, csession):
        out = csession.execute("EXPLAIN SELECT k FROM t LIMIT 2 OFFSET 5")
        text = "\n".join(out.column(out.column_names[0]).to_pylist())
        assert "offset=5" in text

    def test_simple_case_form(self, csession):
        out = csession.execute(
            "SELECT CASE k WHEN 1 THEN 'one' WHEN 2 THEN 'two' ELSE 'many' END"
            " AS w FROM t ORDER BY k"
        )
        assert out.column("w").to_pylist() == ["one", "two", "many", "many", "many"]
        # NULL operand matches no WHEN → ELSE
        out = csession.execute(
            "SELECT CASE nullif(k, 1) WHEN 1 THEN 'x' ELSE 'e' END AS w"
            " FROM t WHERE k = 1"
        )
        assert out.column("w").to_pylist() == ["e"]

    def test_simple_case_in_correlated_contexts(self, tmp_warehouse):
        """Simple-CASE operands/values must be visible to projection
        pruning AND correlated-subquery scope resolution (fuzz + review
        r5 findings)."""
        cat = LakeSoulCatalog(str(tmp_warehouse))
        s = SqlSession(cat)
        s.execute("CREATE TABLE o (rid bigint, k bigint, s string)")
        s.execute("CREATE TABLE i (k bigint, b double, rid2 bigint)")
        s.execute("INSERT INTO o VALUES (1, 1, 'red'), (2, 2, 'blue'), (3, 9, NULL)")
        s.execute("INSERT INTO i VALUES (1, 5.0, 1), (2, 1.5, 2)")
        out = s.execute(
            "SELECT rid FROM o WHERE EXISTS (SELECT 1 FROM i WHERE i.k = o.k"
            " AND i.b > CASE o.s WHEN 'red' THEN 1 ELSE 2 END)"
        )
        assert out.column("rid").to_pylist() == [1]
        out = s.execute(
            "SELECT CASE k WHEN (SELECT max(k) FROM i WHERE i.rid2 = o.rid)"
            " THEN 1 ELSE 0 END AS c FROM o ORDER BY rid"
        )
        assert out.column("c").to_pylist() == [1, 1, 0]

    def test_case_over_aggregates(self, tmp_warehouse):
        """Aggregates inside CASE conds/operands (searched AND simple forms)
        collect and substitute like any other aggregate expression."""
        cat = LakeSoulCatalog(str(tmp_warehouse))
        s = SqlSession(cat)
        s.execute("CREATE TABLE t (k bigint, a double)")
        s.execute(
            "INSERT INTO t VALUES (1, 1.0), (1, 2.0), (1, 3.0), (2, 4.0)"
        )
        out = s.execute(
            "SELECT k, CASE WHEN count(*) > 2 THEN 'big' ELSE 'small' END AS c"
            " FROM t GROUP BY k ORDER BY k"
        )
        assert out.column("c").to_pylist() == ["big", "small"]
        out = s.execute(
            "SELECT k, CASE count(*) WHEN 3 THEN 'three' ELSE 'other' END AS c"
            " FROM t GROUP BY k ORDER BY k"
        )
        assert out.column("c").to_pylist() == ["three", "other"]


class TestDmlExpressions:
    """UPDATE SET <expr> and general (non-pushdown) WHERE predicates for
    UPDATE/DELETE (r5) — DataFusion accepts arbitrary expressions here."""

    @pytest.fixture()
    def dsession(self, tmp_warehouse):
        cat = LakeSoulCatalog(str(tmp_warehouse))
        s = SqlSession(cat)
        s.execute("CREATE TABLE t (k bigint PRIMARY KEY, v double, s string)")
        s.execute(
            "INSERT INTO t VALUES (1, -1.5, 'low'), (2, 2.5, 'high'),"
            " (3, -3.5, 'LOW')"
        )
        return s

    def test_update_set_expression(self, dsession):
        out = dsession.execute("UPDATE t SET v = abs(v) WHERE lower(s) = 'low'")
        assert out.column("updated").to_pylist() == [2]
        got = dsession.execute("SELECT v FROM t ORDER BY k")
        assert got.column("v").to_pylist() == [1.5, 2.5, 3.5]

    def test_update_set_arithmetic_on_self(self, dsession):
        dsession.execute("UPDATE t SET v = v * 2 + 1 WHERE k = 2")
        got = dsession.execute("SELECT v FROM t WHERE k = 2")
        assert got.column("v").to_pylist() == [6.0]

    def test_update_set_case_expression(self, dsession):
        dsession.execute(
            "UPDATE t SET s = CASE WHEN v > 0 THEN 'pos' ELSE 'neg' END"
            " WHERE k > 0"
        )
        got = dsession.execute("SELECT s FROM t ORDER BY k")
        assert got.column("s").to_pylist() == ["neg", "pos", "neg"]

    def test_delete_with_function_predicate(self, dsession):
        out = dsession.execute("DELETE FROM t WHERE upper(s) = 'LOW'")
        assert out.column("deleted").to_pylist() == [2]
        assert dsession.execute("SELECT count(*) AS c FROM t") \
            .column("c").to_pylist() == [1]

    def test_delete_with_subquery_predicate(self, dsession):
        dsession.execute("CREATE TABLE dead (k bigint)")
        dsession.execute("INSERT INTO dead VALUES (1), (3)")
        out = dsession.execute("DELETE FROM t WHERE k IN (SELECT k FROM dead)")
        assert out.column("deleted").to_pylist() == [2]
        assert dsession.execute("SELECT k FROM t").column("k").to_pylist() == [2]

    def test_pushdown_predicates_still_prune(self, dsession):
        # simple predicates keep the Filter fast path (partition pruning)
        out = dsession.execute("UPDATE t SET v = 0 WHERE k = 1")
        assert out.column("updated").to_pylist() == [1]

    def test_pk_update_still_rejected(self, dsession):
        from lakesoul_tpu.errors import LakeSoulError

        with pytest.raises(LakeSoulError, match="primary-key"):
            dsession.execute("UPDATE t SET k = k + 1 WHERE v > 0")

    def test_set_literal_still_works(self, dsession):
        dsession.execute("UPDATE t SET s = 'x', v = -1.25 WHERE k = 1")
        got = dsession.execute("SELECT s, v FROM t WHERE k = 1")
        assert got.column("s").to_pylist() == ["x"]
        assert got.column("v").to_pylist() == [-1.25]

    def test_set_expression_evaluates_matched_rows_only(self, tmp_warehouse):
        """A non-matching row must not abort the statement (SQL evaluates
        SET over qualifying rows only): 10 / k with a k=0 row excluded."""
        cat = LakeSoulCatalog(str(tmp_warehouse))
        s = SqlSession(cat)
        s.execute("CREATE TABLE z (k bigint, v double)")
        s.execute("INSERT INTO z VALUES (0, 1.0), (2, 1.0), (5, 1.0)")
        s.execute("UPDATE z SET v = 10 / k WHERE k > 0")
        got = s.execute("SELECT k, v FROM z ORDER BY k")
        assert got.column("v").to_pylist() == [1.0, 5.0, 2.0]

    def test_dml_subquery_sees_pre_statement_snapshot(self, tmp_warehouse):
        """A self-referencing uncorrelated subquery evaluates ONCE per
        statement: partition 1's committed rewrite must not change
        partition 2's predicate."""
        cat = LakeSoulCatalog(str(tmp_warehouse))
        s = SqlSession(cat)
        s.execute(
            "CREATE TABLE p (d string, k bigint, v double) PARTITIONED BY (d)"
        )
        s.execute(
            "INSERT INTO p VALUES ('a', 1, 9.0), ('a', 2, 1.0),"
            " ('b', 3, 9.0), ('b', 4, 2.0)"
        )
        # max(v) = 9.0 pre-statement; both 9.0 rows (one per partition)
        # must update even though the first partition's commit lowers max
        out = s.execute(
            "UPDATE p SET v = 0 WHERE v = (SELECT max(v) FROM p)"
        )
        assert out.column("updated").to_pylist() == [2]
        got = s.execute("SELECT count(*) AS c FROM p WHERE v = 0")
        assert got.column("c").to_pylist() == [2]
        # the memo is statement-scoped: a fresh statement re-evaluates
        # against the updated data (max is now 2.0 → exactly one row)
        out = s.execute("DELETE FROM p WHERE v = (SELECT max(v) FROM p)")
        assert out.column("deleted").to_pylist() == [1]


class TestGroupByExpressions:
    """GROUP BY <expr> (r5) — the BI staple: GROUP BY upper(s), bucketed
    arithmetic, CASE buckets."""

    @pytest.fixture()
    def gsession(self, tmp_warehouse):
        cat = LakeSoulCatalog(str(tmp_warehouse))
        s = SqlSession(cat)
        s.execute("CREATE TABLE t (k bigint, s string, v double)")
        s.execute(
            "INSERT INTO t VALUES (1,'red',1.0), (2,'RED',2.0),"
            " (3,'blue',3.0), (14,'Red',4.0)"
        )
        return s

    def test_group_by_function(self, gsession):
        out = gsession.execute(
            "SELECT upper(s) AS u, count(*) AS n, sum(v) AS sv FROM t"
            " GROUP BY upper(s) ORDER BY u"
        )
        assert out.column("u").to_pylist() == ["BLUE", "RED"]
        assert out.column("n").to_pylist() == [1, 3]
        assert out.column("sv").to_pylist() == [3.0, 7.0]

    def test_group_by_arithmetic_bucket(self, gsession):
        out = gsession.execute(
            "SELECT k / 10 AS b, count(*) AS n FROM t GROUP BY k / 10 ORDER BY b"
        )
        assert out.column("b").to_pylist() == [0, 1]
        assert out.column("n").to_pylist() == [3, 1]

    def test_group_by_case(self, gsession):
        out = gsession.execute(
            "SELECT CASE WHEN v > 2 THEN 'hi' ELSE 'lo' END AS b, count(*) AS n"
            " FROM t GROUP BY CASE WHEN v > 2 THEN 'hi' ELSE 'lo' END ORDER BY b"
        )
        assert out.column("b").to_pylist() == ["hi", "lo"]
        assert out.column("n").to_pylist() == [2, 2]

    def test_group_expr_without_projecting_it(self, gsession):
        out = gsession.execute(
            "SELECT count(*) AS n FROM t GROUP BY upper(s) ORDER BY n DESC"
        )
        assert out.column("n").to_pylist() == [3, 1]

    def test_mixed_column_and_expr_keys(self, gsession):
        gsession.execute("INSERT INTO t VALUES (5, 'red', 9.0)")
        out = gsession.execute(
            "SELECT s, k / 10 AS b, count(*) AS n FROM t"
            " GROUP BY s, k / 10 ORDER BY s, b"
        )
        # ('RED',0), ('Red',1), ('blue',0), ('red',0 ×2)
        assert out.column("n").to_pylist() == [1, 1, 1, 2]

    def test_plain_group_by_unchanged(self, gsession):
        out = gsession.execute(
            "SELECT s, count(*) AS n FROM t GROUP BY s ORDER BY s"
        )
        assert out.num_rows == 4  # case-sensitive distinct values

    def test_having_on_group_expression(self, gsession):
        out = gsession.execute(
            "SELECT upper(s) AS u, count(*) AS n FROM t"
            " GROUP BY upper(s) HAVING upper(s) = 'RED'"
        )
        assert out.column("u").to_pylist() == ["RED"]
        assert out.column("n").to_pylist() == [3]

    def test_expression_on_top_of_group_key(self, gsession):
        out = gsession.execute(
            "SELECT k / 10 + 1 AS b1, count(*) AS n FROM t"
            " GROUP BY k / 10 ORDER BY b1"
        )
        assert out.column("b1").to_pylist() == [1, 2]

    def test_qualifier_insensitive_key_match(self, gsession):
        out = gsession.execute(
            "SELECT upper(t.s) AS u, count(*) AS n FROM t"
            " GROUP BY upper(s) ORDER BY u"
        )
        assert out.column("u").to_pylist() == ["BLUE", "RED"]

    def test_group_by_ordinal(self, gsession):
        out = gsession.execute(
            "SELECT upper(s) AS u, count(*) AS n FROM t GROUP BY 1 ORDER BY u"
        )
        assert out.column("u").to_pylist() == ["BLUE", "RED"]
        with pytest.raises(SqlError, match="out of range"):
            gsession.execute("SELECT s, count(*) FROM t GROUP BY 9")
        with pytest.raises(SqlError, match="literal"):
            gsession.execute("SELECT s, count(*) FROM t GROUP BY 'x'")

    def test_non_grouped_reference_clean_error(self, gsession):
        with pytest.raises(SqlError, match="GROUP BY"):
            gsession.execute("SELECT v, count(*) AS n FROM t GROUP BY upper(s)")


class TestStringFunctions:
    """trim/ltrim/rtrim/replace/concat (r5)."""

    @pytest.fixture()
    def ssession(self, tmp_warehouse):
        cat = LakeSoulCatalog(str(tmp_warehouse))
        s = SqlSession(cat)
        s.execute("CREATE TABLE t (k bigint, s string)")
        s.execute(
            "INSERT INTO t VALUES (1, '  pad  '), (2, 'a-b-c'), (3, NULL)"
        )
        return s

    def test_trims(self, ssession):
        out = ssession.execute(
            "SELECT trim(s) AS t, ltrim(s) AS l, rtrim(s) AS r FROM t WHERE k = 1"
        )
        assert out.column("t").to_pylist() == ["pad"]
        assert out.column("l").to_pylist() == ["pad  "]
        assert out.column("r").to_pylist() == ["  pad"]

    def test_replace(self, ssession):
        out = ssession.execute(
            "SELECT replace(s, '-', '_') AS r FROM t WHERE k = 2"
        )
        assert out.column("r").to_pylist() == ["a_b_c"]

    def test_concat(self, ssession):
        out = ssession.execute(
            "SELECT concat(s, ':', cast(k AS string)) AS c FROM t ORDER BY k"
        )
        # NULL arguments are SKIPPED (Postgres/DataFusion semantics)
        assert out.column("c").to_pylist() == ["  pad  :1", "a-b-c:2", ":3"]

    def test_concat_single_arg_and_null_literals(self, ssession):
        out = ssession.execute("SELECT concat(s) AS c FROM t WHERE k = 2")
        assert out.column("c").to_pylist() == ["a-b-c"]
        out = ssession.execute(
            "SELECT replace(s, NULL, 'x') AS r FROM t WHERE k = 2"
        )
        assert out.column("r").to_pylist() == [None]  # NULL arg nulls result

    def test_nested_and_in_where(self, ssession):
        out = ssession.execute(
            "SELECT k FROM t WHERE trim(replace(s, '-', ' ')) = 'a b c'"
        )
        assert out.column("k").to_pylist() == [2]

    def test_date_parts(self, tmp_warehouse):
        cat = LakeSoulCatalog(str(tmp_warehouse))
        s = SqlSession(cat)
        s.execute("CREATE TABLE d (k bigint, ts timestamp, dt date)")
        s.execute(
            "INSERT INTO d VALUES (1, TIMESTAMP '2026-07-30 12:34:56',"
            " DATE '2025-02-28')"
        )
        out = s.execute(
            "SELECT year(ts) AS y, month(ts) AS m, day(dt) AS d2 FROM d"
        )
        assert out.column("y").to_pylist() == [2026]
        assert out.column("m").to_pylist() == [7]
        assert out.column("d2").to_pylist() == [28]
        # grouping by a date part — the BI time-bucket staple
        s.execute(
            "INSERT INTO d VALUES (2, TIMESTAMP '2026-08-01 00:00:00',"
            " DATE '2025-03-01')"
        )
        out = s.execute(
            "SELECT month(ts) AS m, count(*) AS n FROM d GROUP BY month(ts)"
            " ORDER BY m"
        )
        assert out.column("m").to_pylist() == [7, 8]
        with pytest.raises(SqlError, match="date/timestamp"):
            s.execute("SELECT year(k) FROM d")

    def test_extract_and_time_parts(self, tmp_warehouse):
        cat = LakeSoulCatalog(str(tmp_warehouse))
        s = SqlSession(cat)
        s.execute("CREATE TABLE e (ts timestamp)")
        s.execute("INSERT INTO e VALUES (TIMESTAMP '2026-07-30 12:34:56')")
        out = s.execute(
            "SELECT EXTRACT(year FROM ts) AS y, EXTRACT(month FROM ts) AS m,"
            " hour(ts) AS h, minute(ts) AS mi, second(ts) AS sec FROM e"
        )
        assert out.column("y").to_pylist() == [2026]
        assert out.column("m").to_pylist() == [7]
        assert out.column("h").to_pylist() == [12]
        assert out.column("mi").to_pylist() == [34]
        assert out.column("sec").to_pylist() == [56]
        with pytest.raises(SqlError, match="not supported"):
            s.execute("SELECT EXTRACT(epoch FROM ts) FROM e")
        # extract as a soft ident: a column named extract keeps working
        s.execute("CREATE TABLE x (extract bigint)")
        s.execute("INSERT INTO x VALUES (5)")
        assert s.execute("SELECT extract FROM x").column("extract").to_pylist() == [5]

    def test_time_parts_of_date_are_zero(self, tmp_warehouse):
        cat = LakeSoulCatalog(str(tmp_warehouse))
        s = SqlSession(cat)
        s.execute("CREATE TABLE dd (d date)")
        s.execute("INSERT INTO dd VALUES (DATE '2026-07-30')")
        out = s.execute("SELECT hour(d) AS h, EXTRACT(second FROM d) AS s2 FROM dd")
        assert out.column("h").to_pylist() == [0]
        assert out.column("s2").to_pylist() == [0]

    def test_set_expression_subquery_snapshot_on_pushdown_where(self, tmp_warehouse):
        """The snapshot memo arms even when WHERE is fully pushdown: a SET
        subquery must not see partition 1's rewrite from partition 2."""
        cat = LakeSoulCatalog(str(tmp_warehouse))
        s = SqlSession(cat)
        s.execute("CREATE TABLE t (p string, v bigint) PARTITIONED BY (p)")
        s.execute("INSERT INTO t VALUES ('a', 1), ('b', 10)")
        s.execute("UPDATE t SET v = v + (SELECT sum(v) FROM t) WHERE v >= 0")
        out = s.execute("SELECT v FROM t ORDER BY v")
        assert out.column("v").to_pylist() == [12, 21]

    def test_correlated_subquery_rejects_limit_offset(self, tmp_warehouse):
        cat = LakeSoulCatalog(str(tmp_warehouse))
        s = SqlSession(cat)
        s.execute("CREATE TABLE o (k bigint)")
        s.execute("CREATE TABLE i (k bigint, x bigint)")
        s.execute("INSERT INTO o VALUES (1)")
        s.execute("INSERT INTO i VALUES (1, 10)")
        with pytest.raises(SqlError, match="LIMIT/OFFSET"):
            s.execute(
                "SELECT (SELECT max(x) FROM i WHERE i.k = o.k OFFSET 1) FROM o"
            )
        with pytest.raises(SqlError, match="LIMIT/OFFSET"):
            s.execute(
                "SELECT k FROM o WHERE EXISTS"
                " (SELECT 1 FROM i WHERE i.k = o.k LIMIT 1)"
            )


class TestQualifiedOrderGroupOnJoinKeys:
    """ORDER BY / GROUP BY b.k after a RIGHT/FULL join binds the suffixed
    right key, not the NULL-extended left key (high-review r5)."""

    @pytest.fixture()
    def qsession(self, tmp_warehouse):
        cat = LakeSoulCatalog(str(tmp_warehouse))
        s = SqlSession(cat)
        s.execute("CREATE TABLE a (k bigint)")
        s.execute("CREATE TABLE b (k bigint, y double)")
        s.execute("INSERT INTO a VALUES (1), (3)")
        s.execute("INSERT INTO b VALUES (3, 1.0), (3, 2.0), (5, 3.0)")
        return s

    def test_order_by_right_key(self, qsession):
        out = qsession.execute(
            "SELECT b.k AS bk FROM a RIGHT JOIN b ON a.k = b.k ORDER BY b.k DESC"
        )
        assert out.column("bk").to_pylist() == [5, 3, 3]

    def test_group_by_right_key(self, qsession):
        out = qsession.execute(
            "SELECT b.k AS bk, count(*) AS n FROM a RIGHT JOIN b ON a.k = b.k"
            " GROUP BY b.k ORDER BY bk"
        )
        assert out.column("bk").to_pylist() == [3, 5]
        assert out.column("n").to_pylist() == [2, 1]

    def test_left_qualifier_still_left(self, qsession):
        out = qsession.execute(
            "SELECT a.k AS ak FROM a FULL OUTER JOIN b ON a.k = b.k"
            " ORDER BY a.k"
        )
        # NULL-extended left keys sort last (pyarrow default)
        assert out.column("ak").to_pylist() == [1, 3, 3, None]
