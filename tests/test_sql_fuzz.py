"""Randomized SQL differential fuzz: generated queries vs a pandas oracle.

The TPC-H harness pins 22 fixed query shapes against pandas; this fuzz
complements it with RANDOM compositions of the round-5 surface — inner /
left / right / full joins, scalar functions (coalesce, abs, round, upper,
length, cast), simple and searched CASE, WHERE comparisons, GROUP BY
aggregates, ORDER BY and LIMIT/OFFSET — executed by the engine and
re-computed independently with pandas, row-for-row (the reference's
random-query benchmark role, SURVEY §4).

Data contains NULLs in non-key columns, so three-valued comparisons and
NULL-extended outer-join rows are exercised throughout; every query
carries a deterministic total ORDER BY so result comparison is exact.
"""

import numpy as np
import pandas as pd
import pyarrow as pa
import pytest

from lakesoul_tpu import LakeSoulCatalog
from lakesoul_tpu.sql import SqlSession

N_SEEDS = 120

# (SQL join spelling, pandas merge how) — shared by every join shape so a
# one-sided edit cannot silently narrow one shape's coverage
JOIN_KINDS = [
    ("JOIN", "inner"), ("LEFT JOIN", "left"),
    ("RIGHT JOIN", "right"), ("FULL OUTER JOIN", "outer"),
]


def _frames(rng):
    n1 = int(rng.integers(8, 40))
    n2 = int(rng.integers(8, 40))
    t1 = pd.DataFrame({
        "k": rng.integers(0, 12, n1).astype("int64"),
        "a": np.round(rng.normal(size=n1), 3),
        "s": rng.choice(["red", "green", "blue", "RED"], n1),
        "rid": np.arange(n1, dtype="int64"),  # unique: total order anchor
    })
    t2 = pd.DataFrame({
        "k": rng.integers(0, 12, n2).astype("int64"),
        "b": np.round(rng.normal(size=n2), 3),
        "rid2": np.arange(n2, dtype="int64"),
    })
    # NULLs in non-key columns (object dtype keeps None, not NaN coercion)
    t1.loc[rng.random(n1) < 0.15, "a"] = None
    t1.loc[rng.random(n1) < 0.15, "s"] = None
    t2.loc[rng.random(n2) < 0.15, "b"] = None
    return t1, t2


def _session(tmp_path, t1, t2):
    cat = LakeSoulCatalog(str(tmp_path / "wh"))
    s = SqlSession(cat)
    s.execute("CREATE TABLE t1 (k bigint, a double, s string, rid bigint)")
    s.execute("CREATE TABLE t2 (k bigint, b double, rid2 bigint)")
    cat.table("t1").write_arrow(pa.Table.from_pandas(t1, preserve_index=False))
    cat.table("t2").write_arrow(pa.Table.from_pandas(t2, preserve_index=False))
    return s


# ---------------------------------------------------------------- oracles
def _oracle_scalar(df, rng):
    """(sql expr, pandas series, name) for a random scalar projection."""
    pick = rng.integers(0, 7)
    if pick == 0:
        return "coalesce(s, 'none')", df["s"].fillna("none"), "e"
    if pick == 1:
        return "abs(a)", df["a"].abs(), "e"
    if pick == 2:
        # SQL rounds half away from zero; numpy rounds half to even —
        # avoid exact .5 ties by the data's 3-decimal rounding + offset
        return "round(a + 0.001, 1)", (
            np.sign(df["a"] + 0.001)
            * np.floor(np.abs(df["a"] + 0.001) * 10 + 0.5) / 10
        ), "e"
    if pick == 3:
        return "upper(s)", df["s"].str.upper(), "e"
    if pick == 4:
        return "length(s)", df["s"].str.len().astype("Int64"), "e"
    if pick == 5:
        return "cast(k AS string)", df["k"].astype("string"), "e"
    return (
        "CASE s WHEN 'red' THEN 1 WHEN 'blue' THEN 2 ELSE 0 END",
        df["s"].map({"red": 1, "blue": 2}).fillna(0).astype("int64"),
        "e",
    )


def _compare(got: pa.Table, want: pd.DataFrame):
    got_df = got.to_pandas()
    assert len(got_df) == len(want), (len(got_df), len(want))
    for col in want.columns:
        g = got_df[col].tolist()
        w = want[col].tolist()
        for gv, wv in zip(g, w):
            g_null = gv is None or (isinstance(gv, float) and np.isnan(gv))
            w_null = wv is None or (
                isinstance(wv, float) and np.isnan(wv)
            ) or wv is pd.NA
            if g_null or w_null:
                assert g_null and w_null, (col, gv, wv)
            elif isinstance(wv, float):
                assert abs(float(gv) - wv) < 1e-6, (col, gv, wv)
            else:
                assert gv == wv, (col, gv, wv)


def _shape_setop(s, t1, t2, rng):
    # set operation between two selections of the same column
    op, fn = [
        ("UNION", lambda l, r: sorted(set(l) | set(r))),
        ("UNION ALL", lambda l, r: sorted(l + r)),
        ("INTERSECT", lambda l, r: sorted(set(l) & set(r))),
        ("EXCEPT", lambda l, r: sorted(set(l) - set(r))),
    ][int(rng.integers(0, 4))]
    c1 = int(rng.integers(2, 10))
    c2 = int(rng.integers(2, 10))
    sql = (
        f"SELECT k FROM t1 WHERE k < {c1} {op}"
        f" SELECT k FROM t2 WHERE k < {c2} ORDER BY k"
    )
    left = t1.loc[t1["k"] < c1, "k"].tolist()
    right = t2.loc[t2["k"] < c2, "k"].tolist()
    want = pd.DataFrame({"k": fn(left, right)}, dtype="int64")
    _compare(s.execute(sql), want)


def _shape_window(s, t1, t2, rng):
    # window function: row_number/rank PARTITION BY k ORDER BY rid
    fn = ["row_number()", "rank()"][int(rng.integers(0, 2))]
    sql = (
        f"SELECT rid, {fn} OVER (PARTITION BY k ORDER BY rid) AS w"
        " FROM t1 ORDER BY rid"
    )
    want = t1.sort_values("rid").copy()
    # rid is unique, so rank == row_number within each partition
    want["w"] = want.groupby("k")["rid"].rank(method="first").astype("int64")
    want = want[["rid", "w"]].sort_values("rid").reset_index(drop=True)
    _compare(s.execute(sql), want)


def _shape_having(s, t1, t2, rng):
    # HAVING over a grouped aggregate
    thresh = int(rng.integers(1, 5))
    sql = (
        "SELECT k, count(*) AS n FROM t1 GROUP BY k"
        f" HAVING count(*) >= {thresh} ORDER BY k"
    )
    grouped = t1.groupby("k").size()
    grouped = grouped[grouped >= thresh]
    want = pd.DataFrame({
        "k": grouped.index.astype("int64"), "n": grouped.values.astype("int64"),
    }).sort_values("k").reset_index(drop=True)
    _compare(s.execute(sql), want)


def _shape_join_where(s, t1, t2, rng):
    # join of a random kind + POST-JOIN WHERE on one side's payload
    # (under right/full joins the predicate must not push below the
    # join — it would drop NULL-extended rows' partners)
    kind, how = JOIN_KINDS[int(rng.integers(0, len(JOIN_KINDS)))]
    col = "a" if rng.random() < 0.5 else "b"
    lo = float(np.round(rng.normal(), 2))
    sql = (
        f"SELECT rid, rid2 FROM t1 {kind} t2 ON t1.k = t2.k"
        f" WHERE {col} > {lo} ORDER BY rid, rid2"
    )
    merged = t1.merge(t2, on="k", how=how)
    want = merged.loc[merged[col] > lo, ["rid", "rid2"]]
    want = want.sort_values(
        ["rid", "rid2"], na_position="last"
    ).reset_index(drop=True)
    _compare(s.execute(sql), want)


def _shape_in_subquery(s, t1, t2, rng):
    # [NOT] IN subquery with SQL three-valued logic: probe side (t1.a)
    # and subquery side (t2.b) both carry NULLs
    negated = rng.random() < 0.5
    with_where = rng.random() < 0.5
    c = float(np.round(rng.normal(), 2))
    where = f" WHERE b > {c}" if with_where else ""
    sql = (
        f"SELECT rid FROM t1 WHERE a {'NOT ' if negated else ''}IN"
        f" (SELECT b FROM t2{where}) ORDER BY rid"
    )
    sub = t2.loc[t2["b"] > c, "b"] if with_where else t2["b"]
    values = set(sub.dropna().tolist())
    set_has_null = bool(sub.isna().any())
    set_empty = len(sub) == 0
    keep = []
    for _, row in t1.iterrows():
        x = row["a"]
        x_null = pd.isna(x)
        if not negated:
            keep.append((not x_null) and x in values)
        elif set_empty:
            keep.append(True)  # NOT IN () is TRUE, even for NULL x
        else:
            keep.append(
                (not x_null) and (not set_has_null) and x not in values
            )
    want = pd.DataFrame({"rid": t1.loc[keep, "rid"]})
    want = want.sort_values("rid").reset_index(drop=True)
    _compare(s.execute(sql), want)


def _shape_scalar_where(s, t1, t2, rng):
    # single table: scalar expr + WHERE + ORDER + LIMIT/OFFSET
    expr, series, name = _oracle_scalar(t1, rng)
    lo = float(np.round(rng.normal(), 2))
    limit = int(rng.integers(1, 20))
    offset = int(rng.integers(0, 5))
    sql = (
        f"SELECT rid, {expr} AS {name} FROM t1 WHERE a > {lo}"
        f" ORDER BY rid LIMIT {limit} OFFSET {offset}"
    )
    mask = t1["a"] > lo  # NaN > x is False: matches SQL NULL → filtered
    want = pd.DataFrame({"rid": t1.loc[mask, "rid"], name: series[mask]})
    want = want.sort_values("rid").iloc[offset:offset + limit]
    _compare(s.execute(sql), want.reset_index(drop=True))


def _shape_join(s, t1, t2, rng):
    # two-table join of a random kind, keys + one payload per side
    kind, how = JOIN_KINDS[int(rng.integers(0, len(JOIN_KINDS)))]
    sql = (
        f"SELECT rid, rid2, a, b FROM t1 {kind} t2 ON t1.k = t2.k"
        " ORDER BY rid, rid2"
    )
    want = t1.merge(t2, on="k", how=how)[["rid", "rid2", "a", "b"]]
    want = want.sort_values(
        ["rid", "rid2"], na_position="last"
    ).reset_index(drop=True)
    got = s.execute(sql)
    # engine sorts NULL keys last too (pyarrow default); compare sorted
    _compare(got, want)


def _shape_aggregate(s, t1, t2, rng):
    # aggregate: GROUP BY s with a random aggregate over a
    fn, pdfn = [
        ("count(a)", "count"), ("sum(a)", "sum"), ("min(a)", "min"),
        ("max(a)", "max"), ("avg(a)", "mean"),
    ][int(rng.integers(0, 5))]
    sql = (
        f"SELECT coalesce(s, '?') AS g, {fn} AS v FROM t1"
        " GROUP BY s ORDER BY g"
    )
    g = t1.groupby(t1["s"].fillna("?"), dropna=False)["a"]
    # SQL semantics: SUM over an all-NULL group is NULL, not pandas' 0.0
    grouped = g.sum(min_count=1) if pdfn == "sum" else g.agg(pdfn)
    want = pd.DataFrame({"g": grouped.index, "v": grouped.values})
    if pdfn == "count":
        want["v"] = want["v"].astype("int64")
    want = want.sort_values("g").reset_index(drop=True)
    _compare(s.execute(sql), want)



def _shape_update_dml(s, t1, t2, rng):
    """MUTATING shape — must stay LAST in _SHAPES: random expression
    UPDATE followed by a full-table readback vs the pandas-applied
    mutation."""
    lo = float(np.round(rng.normal(), 2))
    expr, series = [
        ("abs(a) + 1", t1["a"].abs() + 1),
        ("a * 2", t1["a"] * 2),
        ("coalesce(a, 0.0)", t1["a"].fillna(0.0)),
    ][int(rng.integers(0, 3))]
    # the OR IS NULL arm makes coalesce's NULL branch reachable (WHERE a >
    # lo alone can never match a NULL row in engine or oracle)
    where = f"a > {lo} OR a IS NULL"
    out = s.execute(f"UPDATE t1 SET a = {expr} WHERE {where}")
    mask = (t1["a"] > lo) | t1["a"].isna()
    assert out.column("updated").to_pylist() == [int(mask.sum())]
    want = t1.copy()
    want.loc[mask, "a"] = series[mask]
    want = want[["rid", "a"]].sort_values("rid").reset_index(drop=True)
    _compare(s.execute("SELECT rid, a FROM t1 ORDER BY rid"), want)


_SHAPES = [
    _shape_scalar_where, _shape_join, _shape_aggregate, _shape_join_where,
    _shape_in_subquery, _shape_window, _shape_having, _shape_setop,
    _shape_update_dml,  # mutates t1: MUST stay last
]


@pytest.mark.parametrize("seed", range(N_SEEDS))
def test_random_query_matches_pandas(tmp_path, seed):
    """EVERY shape runs for EVERY seed (N_SEEDS differential runs per
    shape), each with its own deterministic generator."""
    rng = np.random.default_rng(seed)
    t1, t2 = _frames(rng)
    s = _session(tmp_path, t1, t2)
    assert _SHAPES[-1] is _shape_update_dml  # mutators run last, enforced
    for i, shape in enumerate(_SHAPES):
        shape(s, t1, t2, np.random.default_rng([seed, i]))
