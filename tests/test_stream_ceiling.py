"""Pin the bounded-memory streaming ceiling (VERDICT r3 item 4).

Build and scan run in SEPARATE subprocesses: the scan process's own peak
RSS is the measurement, so writer/generator buffers cannot pollute the
read-path assertion.  If the read path ever regressed to materializing
units, the scan subprocess footprint would blow straight past the
ceiling.  (bench.py's `stream` leg runs the same check at ≥100M-row scale.)

Deflaked (PR 7 satellite).  The old flake — passed in isolation, tripped
only during a busy full run — looked load-sensitive but was not: the scan
child measured itself with ``VmHWM``, and on sandboxed kernels that
emulate /proc (this CI reports "Linux 4.4.0" with a zeroed loadavg —
gVisor), VmHWM is served from the same exec-SURVIVING usage counter as
``ru_maxrss``.  A child forked from a 6 GB pytest process therefore
reported ~6 GB "peak" for a ~430 MB scan; in isolation the parent was
small and the number looked sane.  Proven by ballooning a parent to 3 GB
and watching a trivial child report 3 GB.  The fix is a measurement that
CANNOT inherit: the child samples its own *current* RSS
(``current_rss_mb``, /proc/self/statm) once per consumed batch and
reports the max — a materializing read keeps its working set resident
while batches yield, so per-batch sampling still catches the regression
this test exists to pin.  ``LAKESOUL_RUNTIME_THREADS`` is pinned so
in-flight decode buffering (workers × batch) is a constant of the test,
not of however many cores the box advertises.
"""

import json
import os
import subprocess
import sys

# decode workers pinned: in-flight buffering (workers × batch) becomes a
# test constant instead of scaling with the CI box's core count
SCAN_THREADS = 4
CEILING_MB = 700
MAX_ATTEMPTS = 2

_BUILD = r"""
import os, sys
sys.path.insert(0, {repo!r})
os.environ["JAX_PLATFORMS"] = "cpu"
import numpy as np, pyarrow as pa
from lakesoul_tpu import LakeSoulCatalog

N, F = 8_000_000, 16
schema = pa.schema([("id", pa.int64())] + [(f"f{{i}}", pa.float32()) for i in range(F)])
cat = LakeSoulCatalog({wh!r})
t = cat.create_table(
    "big", schema, primary_keys=["id"], hash_bucket_num=4,
    properties={{
        "lakesoul.file_format": "lsf",
        "lakesoul.memory_budget_bytes": str(8 << 20),  # 8 MB: force streaming
    }},
)
rng = np.random.default_rng(0)
for start in range(0, N, 1_000_000):
    cols = {{"id": np.arange(start, start + 1_000_000, dtype=np.int64)}}
    for i in range(F):
        cols[f"f{{i}}"] = rng.normal(size=1_000_000).astype(np.float32)
    t.write_arrow(pa.table(cols, schema=schema))
# overlapping upsert so the STREAMING MERGE path runs, not plain decode
up = rng.choice(N, N // 20, replace=False).astype(np.int64)
cols = {{"id": up}}
for i in range(F):
    cols[f"f{{i}}"] = rng.normal(size=len(up)).astype(np.float32)
t.upsert(pa.table(cols, schema=schema))
print("BUILT")
"""

_SCAN = r"""
import json, os, sys
sys.path.insert(0, {repo!r})
os.environ["JAX_PLATFORMS"] = "cpu"
from lakesoul_tpu import LakeSoulCatalog
from lakesoul_tpu.utils.memory import current_rss_mb

t = LakeSoulCatalog({wh!r}).table("big")
rows = 0
peak = current_rss_mb()
for batch in t.scan().batch_size(262_144).to_batches():
    rows += len(batch)
    peak = max(peak, current_rss_mb())
peak = max(peak, current_rss_mb())
print(json.dumps({{"rows": rows, "peak_rss_mb": peak}}))
"""


def _run_scan(repo: str, wh: str) -> dict:
    env = dict(os.environ)
    env["LAKESOUL_RUNTIME_THREADS"] = str(SCAN_THREADS)
    out = subprocess.run(
        [sys.executable, "-c", _SCAN.format(repo=repo, wh=wh)],
        capture_output=True, text=True, timeout=1200, env=env,
    )
    assert out.returncode == 0, out.stderr[-2000:]
    return json.loads(out.stdout.splitlines()[-1])


def test_streaming_scan_stays_under_ceiling(tmp_path):
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    wh = str(tmp_path / "wh")
    build_env = dict(os.environ)
    build_env["LAKESOUL_RUNTIME_THREADS"] = str(SCAN_THREADS)
    built = subprocess.run(
        [sys.executable, "-c", _BUILD.format(repo=repo, wh=wh)],
        capture_output=True, text=True, timeout=1200, env=build_env,
    )
    assert built.returncode == 0, built.stderr[-2000:]

    last = None
    for _attempt in range(MAX_ATTEMPTS):
        last = _run_scan(repo, wh)
        assert last["rows"] == 8_000_000
        # table data ≈ 8M rows x 68 B ≈ 550 MB; a materializing read would
        # hold entire buckets (~140 MB each) plus merge copies on top of
        # the ~250 MB python/pyarrow floor.  The bounded path must stay
        # well below floor+table.  One retry absorbs transient allocator
        # noise; a real materializing regression reproduces every time.
        if last["peak_rss_mb"] < CEILING_MB:
            return
    raise AssertionError(f"streaming scan exceeded the ceiling twice: {last}")
