"""Pin the bounded-memory streaming ceiling (VERDICT r3 item 4).

Build and scan run in SEPARATE subprocesses: the scan process's own peak
RSS is the measurement, so writer/generator buffers (and whatever the rest
of a busy CI box is doing during the build) cannot pollute the read-path
assertion.  If the read path ever regressed to materializing units, the
scan subprocess high-water mark would blow straight past the ceiling.
(bench.py's `stream` leg runs the same check at ≥100M-row scale.)
"""

import json
import os
import subprocess
import sys

_BUILD = r"""
import os, sys
sys.path.insert(0, {repo!r})
os.environ["JAX_PLATFORMS"] = "cpu"
import numpy as np, pyarrow as pa
from lakesoul_tpu import LakeSoulCatalog

N, F = 8_000_000, 16
schema = pa.schema([("id", pa.int64())] + [(f"f{{i}}", pa.float32()) for i in range(F)])
cat = LakeSoulCatalog({wh!r})
t = cat.create_table(
    "big", schema, primary_keys=["id"], hash_bucket_num=4,
    properties={{
        "lakesoul.file_format": "lsf",
        "lakesoul.memory_budget_bytes": str(8 << 20),  # 8 MB: force streaming
    }},
)
rng = np.random.default_rng(0)
for start in range(0, N, 1_000_000):
    cols = {{"id": np.arange(start, start + 1_000_000, dtype=np.int64)}}
    for i in range(F):
        cols[f"f{{i}}"] = rng.normal(size=1_000_000).astype(np.float32)
    t.write_arrow(pa.table(cols, schema=schema))
# overlapping upsert so the STREAMING MERGE path runs, not plain decode
up = rng.choice(N, N // 20, replace=False).astype(np.int64)
cols = {{"id": up}}
for i in range(F):
    cols[f"f{{i}}"] = rng.normal(size=len(up)).astype(np.float32)
t.upsert(pa.table(cols, schema=schema))
print("BUILT")
"""

_SCAN = r"""
import json, os, sys
sys.path.insert(0, {repo!r})
os.environ["JAX_PLATFORMS"] = "cpu"
from lakesoul_tpu import LakeSoulCatalog
from lakesoul_tpu.utils.memory import peak_rss_mb

t = LakeSoulCatalog({wh!r}).table("big")
rows = 0
for batch in t.scan().batch_size(262_144).to_batches():
    rows += len(batch)
print(json.dumps({{"rows": rows, "peak_rss_mb": peak_rss_mb()}}))
"""


def test_streaming_scan_stays_under_ceiling(tmp_path):
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    wh = str(tmp_path / "wh")
    built = subprocess.run(
        [sys.executable, "-c", _BUILD.format(repo=repo, wh=wh)],
        capture_output=True, text=True, timeout=1200,
    )
    assert built.returncode == 0, built.stderr[-2000:]
    out = subprocess.run(
        [sys.executable, "-c", _SCAN.format(repo=repo, wh=wh)],
        capture_output=True, text=True, timeout=1200,
    )
    assert out.returncode == 0, out.stderr[-2000:]
    r = json.loads(out.stdout.splitlines()[-1])
    assert r["rows"] == 8_000_000
    # table data ≈ 8M rows x 68 B ≈ 550 MB; a materializing read would hold
    # entire buckets (~140 MB each) plus merge copies on top of the ~250 MB
    # python/pyarrow floor.  The bounded path must stay well below
    # floor+table.
    assert r["peak_rss_mb"] < 700, f"streaming scan peak RSS: {r}"
