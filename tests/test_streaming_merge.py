"""Streaming merge-on-read: equivalence with the materialized merge and the
bounded-memory property (VERDICT r1 #1).

The watermark-window merger (io/streaming_merge.py) must produce byte-
identical results to merge_sorted_tables over fully materialized files, for
every PK shape / merge operator / CDC case, while holding peak Arrow
allocation far below the materialized table size."""

import numpy as np
import pyarrow as pa
import pyarrow.parquet as pq
import pytest

from lakesoul_tpu import LakeSoulCatalog
from lakesoul_tpu.io.merge import merge_sorted_tables
from lakesoul_tpu.io.reader import iter_scan_unit_batches, read_scan_unit
from lakesoul_tpu.io.streaming_merge import iter_merged_windows


def _write_sorted_run(path, table, pks):
    """Write one file the way the writer does: sorted by PK, stable."""
    import pyarrow.compute as pc

    order = pa.array(np.arange(len(table), dtype=np.int64))
    idx = pc.sort_indices(
        table.append_column("__row_order", order),
        sort_keys=[(k, "ascending") for k in pks] + [("__row_order", "ascending")],
    )
    pq.write_table(table.take(idx), path, row_group_size=64)


def _merged_equal(a: pa.Table, b: pa.Table):
    assert a.schema.names == b.schema.names
    assert a.num_rows == b.num_rows
    for name in a.schema.names:
        assert a.column(name).to_pylist() == b.column(name).to_pylist(), name


class TestWindowedMergeEquivalence:
    """iter_merged_windows vs merge_sorted_tables on the same runs, with tiny
    stream batches to force many windows and stalls."""

    @pytest.mark.parametrize("batch_rows", [3, 7, 64])
    def test_int_pk_upserts(self, tmp_path, batch_rows):
        rng = np.random.default_rng(0)
        pks = ["id"]
        files = []
        tables = []
        for i in range(4):
            n = 200
            ids = rng.choice(300, n, replace=False).astype(np.int64)
            t = pa.table({"id": ids, "v": rng.normal(size=n), "tag": [f"f{i}"] * n})
            p = str(tmp_path / f"run_{i}_0000.parquet")
            _write_sorted_run(p, t, pks)
            files.append(p)
            tables.append(pq.read_table(p))
        expected = merge_sorted_tables(tables, pks)
        got = pa.concat_tables(
            list(iter_merged_windows(files, pks, stream_batch_rows=batch_rows))
        )
        _merged_equal(expected, got)

    def test_string_pk_with_duplicate_runs(self, tmp_path):
        # heavy duplication: single-key groups span whole stream batches,
        # exercising the stall-resolution path
        pks = ["k"]
        keys = [f"key_{i % 5}" for i in range(150)]
        files, tables = [], []
        for i in range(3):
            t = pa.table({"k": keys, "v": list(range(i * 1000, i * 1000 + 150))})
            p = str(tmp_path / f"dup_{i}_0000.parquet")
            _write_sorted_run(p, t, pks)
            files.append(p)
            tables.append(pq.read_table(p))
        expected = merge_sorted_tables(tables, pks)
        got = pa.concat_tables(
            list(iter_merged_windows(files, pks, stream_batch_rows=4))
        )
        _merged_equal(expected, got)

    @pytest.mark.parametrize("batch_rows", [5, 32])
    def test_composite_pk_and_merge_operators(self, tmp_path, batch_rows):
        rng = np.random.default_rng(1)
        pks = ["a", "b"]
        ops = {"s": "SumAll", "last": "UseLastNotNull", "j": "JoinedAllByComma"}
        files, tables = [], []
        for i in range(3):
            n = 120
            t = pa.table(
                {
                    "a": rng.integers(0, 10, n).astype(np.int64),
                    "b": pa.array([f"b{x}" for x in rng.integers(0, 6, n)]),
                    "s": rng.integers(0, 100, n).astype(np.int64),
                    "last": pa.array(
                        [None if x % 3 == 0 else float(x) for x in range(n)]
                    ),
                    "j": pa.array([f"v{i}_{x % 4}" for x in range(n)]),
                }
            )
            p = str(tmp_path / f"comp_{i}_0000.parquet")
            _write_sorted_run(p, t, pks)
            files.append(p)
            tables.append(pq.read_table(p))
        expected = merge_sorted_tables(tables, pks, merge_operators=ops)
        got = pa.concat_tables(
            list(
                iter_merged_windows(
                    files, pks, merge_operators=ops, stream_batch_rows=batch_rows
                )
            )
        )
        _merged_equal(expected, got)

    def test_pushed_filter_empty_batches_keep_stream_in_watermark(self, tmp_path):
        # regression (r2 review): a pushed-down PK filter can make a stream's
        # early batches empty; the stream must keep fencing the watermark or
        # stale versions of its later keys leak through as duplicates
        import pyarrow.compute as pc

        pks = ["id"]
        n = 10_000
        old = pa.table(
            {"id": np.arange(n, dtype=np.int64), "v": np.zeros(n)}
        )
        new = pa.table(
            {
                "id": np.arange(n - 10, n, dtype=np.int64),
                "v": np.ones(10),
            }
        )
        p0, p1 = str(tmp_path / "old_0000.parquet"), str(tmp_path / "new_0000.parquet")
        _write_sorted_run(p0, old, pks)
        _write_sorted_run(p1, new, pks)
        flt = pc.field("id") >= n - 10
        got = pa.concat_tables(
            list(
                iter_merged_windows(
                    [p0, p1], pks, arrow_filter=flt, stream_batch_rows=64
                )
            )
        ).sort_by("id")
        assert got.column("id").to_pylist() == list(range(n - 10, n))
        assert got.column("v").to_pylist() == [1.0] * 10  # new version won

    def test_null_pk_values_sort_last(self, tmp_path):
        pks = ["id"]
        files, tables = [], []
        for i in range(2):
            t = pa.table(
                {
                    "id": pa.array([1, 2, None, 3, None], type=pa.int64()),
                    "v": [float(i * 10 + j) for j in range(5)],
                }
            )
            p = str(tmp_path / f"null_{i}_0000.parquet")
            _write_sorted_run(p, t, pks)
            files.append(p)
            tables.append(pq.read_table(p))
        expected = merge_sorted_tables(tables, pks)
        got = pa.concat_tables(
            list(iter_merged_windows(files, pks, stream_batch_rows=2))
        )
        _merged_equal(expected, got)

    def test_schema_evolution_missing_column(self, tmp_path):
        pks = ["id"]
        schema = pa.schema(
            [("id", pa.int64()), ("v", pa.float64()), ("extra", pa.string())]
        )
        t0 = pa.table({"id": [1, 2, 3], "v": [1.0, 2.0, 3.0]})  # predates 'extra'
        t1 = pa.table(
            {"id": [2, 4], "v": [20.0, 40.0], "extra": ["x", "y"]},
            schema=schema.remove(0).insert(0, schema.field(0)),
        )
        p0, p1 = str(tmp_path / "a_0000.parquet"), str(tmp_path / "b_0000.parquet")
        _write_sorted_run(p0, t0, pks)
        _write_sorted_run(p1, t1, pks)
        expected = read_scan_unit([p0, p1], pks, schema=schema)
        got = pa.Table.from_batches(
            list(
                iter_scan_unit_batches(
                    [p0, p1], pks, schema=schema, batch_size=2,
                )
            )
        )
        _merged_equal(expected, got)


class TestStreamedScanEquivalence:
    """Whole-table equivalence through the public scan API."""

    def _make_table(self, wh, rows=6000, buckets=2, cdc=False):
        catalog = LakeSoulCatalog(str(wh))
        schema = pa.schema(
            [("id", pa.int64()), ("v", pa.float64()), ("s", pa.string())]
        )
        t = catalog.create_table(
            "st", schema, primary_keys=["id"], hash_bucket_num=buckets, cdc=cdc
        )
        rng = np.random.default_rng(2)
        for wave in range(3):
            ids = rng.choice(rows, rows // 2, replace=False).astype(np.int64)
            data = {
                "id": ids,
                "v": rng.normal(size=len(ids)),
                "s": [f"w{wave}_{i % 17}" for i in range(len(ids))],
            }
            if cdc:
                kinds = ["delete" if i % 11 == 0 else "insert" for i in range(len(ids))]
                data[t.info.cdc_column] = kinds
                t.upsert(pa.table(data, schema=t.schema))
            else:
                t.upsert(pa.table(data, schema=schema))
        return t

    def test_to_batches_matches_to_arrow(self, tmp_warehouse):
        t = self._make_table(tmp_warehouse)
        expected = t.to_arrow().sort_by("id")
        got = pa.Table.from_batches(list(t.scan().batch_size(512).to_batches()))
        _merged_equal(expected, got.sort_by("id"))

    def test_cdc_deletes_dropped_in_stream(self, tmp_warehouse):
        t = self._make_table(tmp_warehouse, cdc=True)
        expected = t.to_arrow().sort_by("id")
        got = pa.Table.from_batches(list(t.scan().to_batches())).sort_by("id")
        _merged_equal(expected, got)

    def test_filter_and_projection_in_stream(self, tmp_warehouse):
        from lakesoul_tpu.io.filters import col

        t = self._make_table(tmp_warehouse)
        scan = t.scan().filter(col("v") > 0).select(["id", "s"])
        expected = scan.to_arrow().sort_by("id")
        got = pa.Table.from_batches(list(scan.to_batches())).sort_by("id")
        _merged_equal(expected, got)


class TestBoundedMemory:
    """VERDICT r1 'done' criterion: reading a bucket whose size exceeds the
    byte budget keeps RSS flat — peak allocation is O(files × stream window),
    independent of bucket row count."""

    def _build(self, catalog, name, n, waves=3):
        schema = pa.schema(
            [("id", pa.int64())] + [(f"f{i}", pa.float64()) for i in range(8)]
        )
        t = catalog.create_table(name, schema, primary_keys=["id"], hash_bucket_num=1)
        rng = np.random.default_rng(3)
        orig_io_config = t.io_config

        def small_rg_config(**overrides):
            cfg = orig_io_config(**overrides)
            cfg.max_row_group_size = 8_192
            return cfg

        t.io_config = small_rg_config
        for _ in range(waves):
            ids = rng.permutation(n).astype(np.int64)
            cols = {"id": ids}
            for i in range(8):
                cols[f"f{i}"] = rng.normal(size=n)
            t.write_arrow(pa.table(cols, schema=schema))
        return t

    def _streamed_peak(self, t, budget) -> tuple[int, int]:
        import gc

        gc.collect()
        base = pa.total_allocated_bytes()
        peak = rows = 0
        for unit in t.scan().scan_plan():
            for b in iter_scan_unit_batches(
                unit.data_files,
                unit.primary_keys,
                batch_size=4096,
                memory_budget_bytes=budget,
                schema=t.schema,
                partition_values=unit.partition_values,
            ):
                rows += len(b)
                peak = max(peak, pa.total_allocated_bytes() - base)
        return peak, rows

    def test_stream_peak_is_flat_in_bucket_size(self, tmp_warehouse):
        catalog = LakeSoulCatalog(str(tmp_warehouse))
        budget = 2 << 20
        small = self._build(catalog, "small", 30_000)
        big = self._build(catalog, "big", 240_000)
        total_input_bytes = 3 * 240_000 * 9 * 8  # 3 runs × 9 float64/int64 cols
        peak_small, rows_small = self._streamed_peak(small, budget)
        peak_big, rows_big = self._streamed_peak(big, budget)
        assert rows_small == 30_000 and rows_big == 240_000
        # 8x the data must NOT mean 8x the peak: the stream window, not the
        # bucket, bounds memory (observed ~2.6x from pool/row-group noise;
        # materializing would scale linearly)
        assert peak_big < peak_small * 4, (peak_small, peak_big)
        # and the peak stays far below even one decoded copy of the inputs
        # (the materialized path holds all runs + merge copies ≈ 2x inputs)
        assert peak_big < total_input_bytes // 2, (peak_big, total_input_bytes)


class TestMixedFormats:
    def test_parquet_and_arrow_ipc_in_one_partition(self, tmp_warehouse):
        """Format registry (VERDICT r1 #4): a partition holding a parquet file
        and an arrow-ipc file reads/merges transparently."""
        catalog = LakeSoulCatalog(str(tmp_warehouse))
        schema = pa.schema([("id", pa.int64()), ("v", pa.float64())])
        t = catalog.create_table("mix", schema, primary_keys=["id"], hash_bucket_num=1)
        t.write_arrow(pa.table({"id": [1, 2, 3], "v": [1.0, 2.0, 3.0]}))

        orig_io_config = t.io_config

        def ipc_config(**overrides):
            cfg = orig_io_config(**overrides)
            cfg.file_format = "arrow"
            return cfg

        t.io_config = ipc_config
        t.upsert(pa.table({"id": [2, 4], "v": [20.0, 40.0]}))
        t.io_config = orig_io_config

        files = [f for u in t.scan().scan_plan() for f in u.data_files]
        exts = {f.rsplit(".", 1)[-1] for f in files}
        assert exts == {"parquet", "arrow"}

        got = t.to_arrow().sort_by("id")
        assert got.column("id").to_pylist() == [1, 2, 3, 4]
        assert got.column("v").to_pylist() == [1.0, 20.0, 3.0, 40.0]

        streamed = pa.Table.from_batches(list(t.scan().to_batches())).sort_by("id")
        _merged_equal(got, streamed)

    def test_arrow_format_roundtrip_and_cdc(self, tmp_warehouse):
        catalog = LakeSoulCatalog(str(tmp_warehouse))
        schema = pa.schema([("id", pa.int64()), ("v", pa.float64())])
        t = catalog.create_table(
            "ipc", schema, primary_keys=["id"], hash_bucket_num=2, cdc=True
        )
        orig_io_config = t.io_config

        def ipc_config(**overrides):
            cfg = orig_io_config(**overrides)
            cfg.file_format = "arrow"
            return cfg

        t.io_config = ipc_config
        from lakesoul_tpu.streaming import CdcIngestor

        ing = CdcIngestor(t)
        ing.apply_many(
            [
                ("insert", {"id": 1, "v": 1.0}),
                ("insert", {"id": 2, "v": 2.0}),
                ("update", {"id": 1, "v": 10.0}),
            ]
        )
        ing.checkpoint(1)
        ing.apply("delete", {"id": 2})
        ing.checkpoint(2)
        got = t.to_arrow()
        assert got.column("id").to_pylist() == [1]
        assert got.column("v").to_pylist() == [10.0]
