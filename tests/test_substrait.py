"""Substrait filter interop (VERDICT r1 missing #6): the scan path accepts
Substrait ExtendedExpression bytes — the wire format external engines emit —
with conservative pushdown (reference: filter/parser.rs:15-27)."""

import numpy as np
import pyarrow as pa
import pyarrow.dataset as pads
import pyarrow.substrait as ps
import pytest

from lakesoul_tpu import LakeSoulCatalog
from lakesoul_tpu.io.filters import Filter, col, filter_column_names

SCHEMA = pa.schema([("id", pa.int64()), ("v", pa.float64()), ("s", pa.string())])


@pytest.fixture()
def table(tmp_warehouse):
    catalog = LakeSoulCatalog(str(tmp_warehouse))
    t = catalog.create_table("sub", SCHEMA, primary_keys=["id"], hash_bucket_num=2)
    t.write_arrow(
        pa.table(
            {
                "id": np.arange(10, dtype=np.int64),
                "v": np.arange(10, dtype=np.float64),
                "s": [f"r{i}" for i in range(10)],
            }
        )
    )
    # upsert flips v for id=3 from 3.0 → 30.0 (the stale 3.0 must never leak)
    t.upsert(pa.table({"id": [3], "v": [30.0], "s": ["new"]}))
    return t


class TestSubstraitRoundTrip:
    def test_own_filter_through_substrait_bytes(self, table):
        flt = col("v") >= 5.0
        data = flt.to_substrait(table.schema)
        direct = table.scan().filter(flt).to_arrow().sort_by("id")
        via = table.scan().filter(Filter.from_substrait(data)).to_arrow().sort_by("id")
        assert direct.equals(via)
        assert via.column("id").to_pylist() == [3, 5, 6, 7, 8, 9]

    def test_external_engine_serialized_expression(self, table):
        # an external engine serializes its own predicate with pyarrow — no
        # framework code involved in producing the bytes
        expr = (pads.field("v") > 2.0) & (pads.field("v") < 8.0)
        data = bytes(ps.serialize_expressions([expr], ["f"], table.schema))
        got = table.scan().filter(Filter.from_substrait(data)).to_arrow().sort_by("id")
        assert got.column("id").to_pylist() == [4, 5, 6, 7]  # 3 has v=30 now

    def test_no_stale_version_resurrection(self, table):
        # predicate matches the OLD version of id=3 (v == 3.0); an unsafe
        # pre-merge pushdown would resurrect the overwritten row
        expr = pads.field("v") == 3.0
        data = bytes(ps.serialize_expressions([expr], ["f"], table.schema))
        got = table.scan().filter(Filter.from_substrait(data)).to_arrow()
        assert got.num_rows == 0

    def test_json_serde_carries_substrait(self, table):
        data = (col("v") >= 5.0).to_substrait(table.schema)
        f = Filter.from_substrait(data)
        round_tripped = Filter.from_json(f.to_json())
        a = table.scan().filter(f).to_arrow().sort_by("id")
        b = table.scan().filter(round_tripped).to_arrow().sort_by("id")
        assert a.equals(b)

    def test_bad_bytes_rejected_eagerly(self):
        with pytest.raises(Exception):
            Filter.from_substrait(b"not substrait")

    def test_column_names_unknowable(self):
        f = Filter(op="substrait", value=b"...")
        assert filter_column_names(f) is None
        assert filter_column_names(col("x") == 1) == {"x"}
        assert filter_column_names((col("x") == 1) & f) is None


class TestSubstraitOverFlight:
    def test_ticket_with_substrait_filter(self, table):
        from lakesoul_tpu.service.flight import LakeSoulFlightClient, LakeSoulFlightServer

        data = (col("v") >= 5.0).to_substrait(table.schema)
        server = LakeSoulFlightServer(table.catalog, "grpc://127.0.0.1:0")
        try:
            client = LakeSoulFlightClient(f"grpc://127.0.0.1:{server.port}")
            got = client.scan("sub", filter=Filter.from_substrait(data)).sort_by("id")
            assert got.column("id").to_pylist() == [3, 5, 6, 7, 8, 9]
        finally:
            server.shutdown()
