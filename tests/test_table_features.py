"""Rollback, schema evolution DDL, writer spill, and page cache tests."""

import numpy as np
import pyarrow as pa
import pytest

from lakesoul_tpu import LakeSoulCatalog
from lakesoul_tpu.errors import ConfigError, MetadataError


SCHEMA = pa.schema([("id", pa.int64()), ("v", pa.float64())])


@pytest.fixture()
def catalog(tmp_warehouse):
    return LakeSoulCatalog(str(tmp_warehouse))


class TestRollback:
    def test_rollback_to_version(self, catalog):
        t = catalog.create_table("t", SCHEMA, primary_keys=["id"], hash_bucket_num=1)
        t.write_arrow(pa.table({"id": [1], "v": [1.0]}))
        t.upsert(pa.table({"id": [1], "v": [2.0]}))
        t.upsert(pa.table({"id": [1], "v": [3.0]}))
        assert t.to_arrow().column("v").to_pylist() == [3.0]
        n = t.rollback(to_version=0)
        assert n == 1
        assert t.to_arrow().column("v").to_pylist() == [1.0]
        # history preserved: the rollback is itself a new version
        head = catalog.client.store.get_latest_partition_info(t.info.table_id, "-5")
        assert head.version == 3

    def test_rollback_to_timestamp(self, catalog):
        import time

        t = catalog.create_table("ts", SCHEMA, primary_keys=["id"], hash_bucket_num=1)
        t.write_arrow(pa.table({"id": [1], "v": [1.0]}))
        ts0 = catalog.client.store.get_latest_partition_info(t.info.table_id, "-5").timestamp
        time.sleep(0.002)
        t.upsert(pa.table({"id": [1], "v": [9.0]}))
        t.rollback(to_timestamp_ms=ts0)
        assert t.to_arrow().column("v").to_pylist() == [1.0]

    def test_rollback_args_validated(self, catalog):
        t = catalog.create_table("bad", SCHEMA)
        with pytest.raises(ConfigError):
            t.rollback()
        with pytest.raises(ConfigError):
            t.rollback(to_version=1, to_timestamp_ms=1)


class TestAddColumns:
    def test_add_column_and_read_old_files(self, catalog):
        t = catalog.create_table("ev", SCHEMA, primary_keys=["id"], hash_bucket_num=1)
        t.write_arrow(pa.table({"id": [1], "v": [1.0]}))
        t.add_columns(pa.field("tag", pa.string()))
        # old file read with null fill
        got = t.to_arrow()
        assert got.column("tag").to_pylist() == [None]
        # new writes carry the column
        t.upsert(pa.table({"id": [2], "v": [2.0], "tag": ["x"]}))
        got = t.to_arrow().sort_by("id")
        assert got.column("tag").to_pylist() == [None, "x"]

    def test_rejects_duplicates_and_non_nullable(self, catalog):
        t = catalog.create_table("ev2", SCHEMA)
        with pytest.raises(MetadataError, match="already exists"):
            t.add_columns(pa.field("v", pa.float64()))
        with pytest.raises(MetadataError, match="nullable"):
            t.add_columns(pa.field("req", pa.int32(), nullable=False))


class TestWriterSpill:
    def test_bounded_memory_auto_flush(self, catalog):
        t = catalog.create_table("spill", SCHEMA, primary_keys=["id"], hash_bucket_num=1)
        cfg = t.io_config(max_file_rows=100)
        from lakesoul_tpu.io.writer import TableWriter

        w = TableWriter(cfg, t.info.table_path)
        for i in range(5):
            w.write_batch(pa.table({"id": np.arange(i * 60, (i + 1) * 60), "v": np.zeros(60)}))
        outs = w.close()
        assert len(outs) >= 3  # spilled into multiple files
        assert w._buffered_rows == 0
        # all rows land and merge fine
        files = {}
        for o in outs:
            files.setdefault(o.partition_desc, []).append(o)
        from lakesoul_tpu.meta import DataFileOp, CommitOp

        catalog.client.commit_data_files(
            t.info,
            {d: [DataFileOp(path=o.path, size=o.size) for o in os_] for d, os_ in files.items()},
            CommitOp.APPEND,
        )
        assert t.to_arrow().num_rows == 300


class TestPageCacheWiring:
    def test_local_paths_bypass_cache(self, tmp_path):
        from lakesoul_tpu.io.object_store import filesystem_for

        opts = {"lakesoul.cache_dir": str(tmp_path / "cache")}
        fs, p = filesystem_for(str(tmp_path / "x.bin"), opts)
        # local paths bypass the cache (no double-copy of local reads)
        assert "Cached" not in type(fs).__name__

    def test_remote_paths_get_cached_fs(self, tmp_path):
        from lakesoul_tpu.io.object_store import filesystem_for

        opts = {"lakesoul.cache_dir": str(tmp_path / "cache")}
        fs, p = filesystem_for("memory://bucket/x.bin", opts)
        assert type(fs).__name__ == "CachedReadFileSystem"


class TestTableProperties:
    """Per-table IO knobs + merge operators persisted in table_info.properties
    (reference: table-level properties JSON) flow into every surface."""

    def test_merge_operators_from_table_properties(self, catalog):
        schema = pa.schema([("id", pa.int64()), ("clicks", pa.int64()), ("tag", pa.string())])
        t = catalog.create_table(
            "agg", schema, primary_keys=["id"], hash_bucket_num=1,
            merge_operators={"clicks": "SumAll", "tag": "JoinedAllByComma"},
        )
        t.write_arrow(pa.table({"id": [1, 2], "clicks": [5, 7], "tag": ["a", "b"]}))
        t.upsert(pa.table({"id": [1], "clicks": [3], "tag": ["c"]}))
        got = t.to_arrow().sort_by("id")
        assert got.column("clicks").to_pylist() == [8, 7]  # SumAll merged
        assert got.column("tag").to_pylist() == ["a,c", "b"]
        # and the config round-trips through a fresh catalog handle
        cfg = catalog.table("agg").io_config()
        assert cfg.merge_operators == {"clicks": "SumAll", "tag": "JoinedAllByComma"}

    def test_merge_operators_via_sql_with_properties(self, catalog):
        from lakesoul_tpu.sql import SqlSession

        sql = SqlSession(catalog)
        sql.execute(
            "CREATE TABLE hits (id bigint PRIMARY KEY, n bigint)"
            " WITH (hashBucketNum = '1', 'mergeOperator.n' = 'SumAll')"
        )
        sql.execute("INSERT INTO hits VALUES (1, 10)")
        sql.execute("INSERT INTO hits VALUES (1, 5)")
        out = sql.execute("SELECT n FROM hits")
        assert out.column("n").to_pylist() == [15]

    def test_io_knobs_from_properties(self, catalog):
        schema = pa.schema([("id", pa.int64()), ("v", pa.float64())])
        t = catalog.create_table(
            "knobs", schema, primary_keys=["id"], hash_bucket_num=1,
            properties={
                "lakesoul.compression": "zstd",
                "lakesoul.compression_level": "3",
                "lakesoul.file_format": "arrow",
                "lakesoul.memory_budget_bytes": str(64 << 20),
            },
        )
        cfg = t.io_config()
        assert cfg.compression == "zstd" and cfg.compression_level == 3
        assert cfg.file_format == "arrow"
        assert cfg.memory_budget_bytes == 64 << 20
        t.write_arrow(pa.table({"id": [1], "v": [1.0]}))
        files = [f for u in t.scan().scan_plan() for f in u.data_files]
        assert files[0].endswith(".arrow")  # the format knob took effect
        assert t.to_arrow().column("v").to_pylist() == [1.0]


class TestSetProperties:
    def test_set_properties_takes_effect(self, catalog):
        t = catalog.create_table("sp1", SCHEMA, primary_keys=["id"], hash_bucket_num=1)
        t.write_arrow(pa.table({"id": [1], "v": [5.0]}))
        t.set_properties({"mergeOperator.v": "SumAll"})
        assert t.io_config().merge_operators == {"v": "SumAll"}
        t.upsert(pa.table({"id": [1], "v": [3.0]}))
        assert t.to_arrow().column("v").to_pylist() == [8.0]  # SumAll now active
        # removal via None
        t.set_properties({"mergeOperator.v": None})
        assert t.io_config().merge_operators == {}

    def test_structural_properties_immutable(self, catalog):
        t = catalog.create_table("sp2", SCHEMA, primary_keys=["id"], hash_bucket_num=2)
        with pytest.raises(MetadataError, match="structural"):
            t.set_properties({"hashBucketNum": "8"})

    def test_alter_set_via_sql(self, catalog):
        from lakesoul_tpu.sql import SqlSession

        sql = SqlSession(catalog)
        sql.execute("CREATE TABLE sp3 (id bigint PRIMARY KEY, n bigint)"
                    " WITH (hashBucketNum = '1')")
        sql.execute("ALTER TABLE sp3 SET ('partition.ttl' = '30', 'mergeOperator.n' = 'SumAll')")
        t = catalog.table("sp3")
        assert t.info.properties["partition.ttl"] == "30"
        sql.execute("INSERT INTO sp3 VALUES (1, 2)")
        sql.execute("INSERT INTO sp3 VALUES (1, 3)")
        assert sql.execute("SELECT n FROM sp3").column("n").to_pylist() == [5]
