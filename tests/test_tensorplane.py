"""Tensor plane: declared tensor columns must validate on write with typed
errors and a real Spark-JSON spelling, DLPack delivery must be provably
zero-copy on host backends, the measured aliasing probe must tell copies
from aliases per dtype, the device-resident replay cache must serve
epoch ≥ 2 byte-identical to epoch 1 (fully resident AND across a budget
spill), permutation must be deterministic under a pinned seed, and the
TPU smoke register must cover 100% of the repo's Pallas kernels with a
complete ``untested_on_tpu`` record on CPU fallback."""

from __future__ import annotations

import numpy as np
import pyarrow as pa
import pytest

from lakesoul_tpu import LakeSoulCatalog
from lakesoul_tpu.errors import ConfigError, TensorColumnError
from lakesoul_tpu.tensorplane import (
    DeviceReplayCache,
    aligned_empty,
    deliver,
    delivery_copies,
    device_put_copies,
    tensor_field,
    tensor_shape_of,
    tensor_specs,
    validate_tensor_batch,
)

SHAPE = (4, 8)
WIDTH = 32


def tensor_schema() -> pa.Schema:
    return pa.schema([
        ("id", pa.int64()),
        tensor_field("emb", SHAPE, "float32"),
        ("label", pa.int32()),
    ])


def tensor_table(n: int, seed: int = 0, schema: pa.Schema | None = None) -> pa.Table:
    schema = schema or tensor_schema()
    rng = np.random.default_rng(seed)
    emb = rng.normal(size=(n, WIDTH)).astype(np.float32)
    return pa.table({
        "id": np.arange(n, dtype=np.int64),
        "emb": pa.FixedSizeListArray.from_arrays(
            pa.array(emb.ravel()), WIDTH
        ).cast(schema.field("emb").type),
        "label": rng.integers(0, 5, n).astype(np.int32),
    }, schema=schema)


@pytest.fixture
def tensor_lsf_table(tmp_warehouse):
    catalog = LakeSoulCatalog(str(tmp_warehouse))
    t = catalog.create_table(
        "tensors", tensor_schema(),
        properties={"lakesoul.file_format": "lsf"},
    )
    t.write_arrow(tensor_table(2048))
    return t


def read_epoch(it) -> list[dict]:
    return [{k: np.copy(np.asarray(v)) for k, v in b.items()} for b in it]


def assert_epochs_byte_identical(a: list[dict], b: list[dict]) -> None:
    assert len(a) == len(b) and len(a) > 0
    for x, y in zip(a, b):
        assert x.keys() == y.keys()
        for k in x:
            assert x[k].dtype == y[k].dtype and x[k].shape == y[k].shape
            assert x[k].tobytes() == y[k].tobytes(), k


# --------------------------------------------------------------- columns


class TestTensorColumns:
    def test_declaration_and_spec(self):
        f = tensor_field("emb", SHAPE, "float32")
        assert pa.types.is_fixed_size_list(f.type)
        assert f.type.list_size == WIDTH
        assert not f.nullable and not f.type.value_field.nullable
        assert tensor_shape_of(f) == SHAPE
        specs = tensor_specs(tensor_schema())
        assert set(specs) == {"emb"}
        assert specs["emb"].shape == SHAPE and specs["emb"].width == WIDTH

    def test_undeclared_fsl_is_one_dimensional_legacy(self):
        f = pa.field("legacy", pa.list_(pa.float32(), 7))
        assert tensor_shape_of(f) == (7,)  # pre-declaration collate contract
        assert tensor_specs(pa.schema([f])) == {}  # never write-validated

    def test_bad_declarations_typed(self):
        with pytest.raises(ConfigError):
            tensor_field("e", (0, 4))
        with pytest.raises(ConfigError):
            tensor_field("e", (4,), "string")
        bad = pa.field(
            "e", pa.list_(pa.float32(), 8),
            metadata={b"lakesoul:tensor": b'{"shape": [3, 3]}'},
        )
        with pytest.raises(ConfigError, match="does not flatten"):
            tensor_shape_of(bad)

    def test_spark_json_round_trip_interop(self):
        """The satellite: fixed_size_list gets a REAL Spark-JSON spelling
        (ArrayType + fixedLength), not the raw-Arrow-name fallback, and it
        round-trips through the wire encoding."""
        import json

        from lakesoul_tpu.meta.entity import schema_from_json, schema_to_json

        schema = tensor_schema()
        doc = json.loads(schema_to_json(schema))
        emb = next(f for f in doc["fields"] if f["name"] == "emb")
        # a Spark reader that ignores the annotation still sees a legal
        # variable-length ArrayType of the right element type
        assert emb["type"]["type"] == "array"
        assert emb["type"]["elementType"] == "float"
        assert emb["type"]["containsNull"] is False
        assert emb["type"]["fixedLength"] == WIDTH
        # the logical shape rides the field's Spark metadata map, so the
        # JSON mirror alone round-trips a multi-dim declaration
        assert emb["metadata"] == {"lakesoul:tensor": {"shape": [4, 8]}}
        back = schema_from_json(schema_to_json(schema))
        assert back.field("emb").type.equals(schema.field("emb").type)
        assert pa.types.is_fixed_size_list(back.field("emb").type)
        assert back.field("emb").type.list_size == WIDTH
        assert tensor_shape_of(back.field("emb")) == SHAPE

    def test_catalog_metadata_survives_ipc_round_trip(self, tmp_warehouse):
        catalog = LakeSoulCatalog(str(tmp_warehouse))
        catalog.create_table("t", tensor_schema())
        reread = catalog.table("t").schema
        assert tensor_shape_of(reread.field("emb")) == SHAPE


# ---------------------------------------------------------------- writer


class TestWriterValidation:
    def test_wrong_width_typed(self, tmp_warehouse):
        catalog = LakeSoulCatalog(str(tmp_warehouse))
        t = catalog.create_table("w", tensor_schema())
        bad = pa.table({
            "id": np.arange(4, dtype=np.int64),
            "emb": pa.FixedSizeListArray.from_arrays(
                pa.array(np.zeros(4 * 16, np.float32)), 16
            ),
            "label": np.zeros(4, np.int32),
        })
        with pytest.raises(TensorColumnError, match="emb.*fixed_size_list\\[16\\]"):
            t.write_arrow(bad)

    def test_wrong_dtype_typed(self, tmp_warehouse):
        catalog = LakeSoulCatalog(str(tmp_warehouse))
        t = catalog.create_table("w2", tensor_schema())
        bad = pa.table({
            "id": np.arange(2, dtype=np.int64),
            "emb": pa.FixedSizeListArray.from_arrays(
                pa.array(np.zeros(2 * WIDTH, np.float64)), WIDTH
            ),
            "label": np.zeros(2, np.int32),
        })
        with pytest.raises(TensorColumnError, match="emb"):
            t.write_arrow(bad)

    def test_null_row_and_missing_column_typed(self, tmp_warehouse):
        catalog = LakeSoulCatalog(str(tmp_warehouse))
        t = catalog.create_table("w3", tensor_schema())
        null_row = pa.table({
            "id": np.arange(2, dtype=np.int64),
            "emb": pa.array(
                [[1.0] * WIDTH, None],
                type=pa.list_(pa.field("element", pa.float32(), False), WIDTH),
            ),
            "label": np.zeros(2, np.int32),
        })
        with pytest.raises(TensorColumnError, match="null row"):
            t.write_arrow(null_row)
        missing = pa.table({
            "id": np.arange(2, dtype=np.int64),
            "label": np.zeros(2, np.int32),
        })
        with pytest.raises(TensorColumnError, match="missing"):
            t.write_arrow(missing)

    def test_validate_helper_direct(self):
        specs = tensor_specs(tensor_schema())
        validate_tensor_batch(tensor_table(8), specs)  # clean passes

    def test_valid_write_lands_and_reads_back(self, tensor_lsf_table):
        got = tensor_lsf_table.scan().to_arrow()
        assert len(got) == 2048
        assert got.schema.field("emb").type.list_size == WIDTH


# ---------------------------------------------------------------- dlpack


class TestDlpackDelivery:
    def test_aligned_empty_alignment(self):
        for shape, dt in [((8,), np.float32), ((3, 5), np.int64), ((2, 2, 2), np.float64)]:
            a = aligned_empty(shape, dt)
            assert a.shape == shape and a.dtype == dt
            assert a.ctypes.data % 64 == 0
            a[:] = 1  # writable

    def test_probe_measures_aliasing_per_dtype(self):
        # CPU CI: float32 is the device dtype → device_put aliases aligned
        # buffers (the PR-9 find); int64/float64 demote → real copies
        assert not device_put_copies(np.float32)
        assert device_put_copies(np.int64)
        assert device_put_copies(np.float64)
        assert not delivery_copies([np.int64, np.float32])  # one alias kills it
        assert delivery_copies([np.int64, np.float64])
        assert not delivery_copies(None)  # unresolved schema: assume aliasing
        assert not delivery_copies([])

    def test_deliver_zero_copy_alias_on_host(self):
        """The tentpole proof on a host backend: the delivered array's
        buffer IS the collate buffer — zero host copies anywhere."""
        src = aligned_empty((64, 8), np.float32)
        src[:] = np.arange(512, dtype=np.float32).reshape(64, 8)
        out = deliver({"x": src})
        assert out["x"].unsafe_buffer_pointer() == src.ctypes.data
        np.testing.assert_array_equal(np.asarray(out["x"]), src)

    def test_deliver_demoted_dtype_still_correct(self):
        src = aligned_empty((16,), np.int64)
        src[:] = np.arange(16)
        out = deliver({"y": src})
        np.testing.assert_array_equal(np.asarray(out["y"]), src)

    def test_collate_output_buffers_are_aligned(self):
        """Windows that span batch boundaries collate into aligned_empty
        buffers, so the delivery hand-off stays zero-copy-capable
        deterministically instead of depending on where malloc landed."""
        from lakesoul_tpu.data.jax_iter import _Rebatcher

        rng = np.random.default_rng(3)
        rb = _Rebatcher(96, tensor_shapes={"emb": SHAPE})
        windows = []
        for i in range(3):  # 3 x 64-row batches → every window spans two
            emb = rng.normal(size=(64, WIDTH)).astype(np.float32)
            windows += rb.push(pa.record_batch(
                pa.table({
                    "id": np.arange(64 * i, 64 * (i + 1), dtype=np.int64),
                    "emb": pa.FixedSizeListArray.from_arrays(
                        pa.array(emb.ravel()), WIDTH
                    ),
                }).combine_chunks().to_batches()[0]
            ))
        assert len(windows) == 2
        for w in windows:
            assert len(w.parts) == 2 and w.fast  # genuinely multi-part
            out = w.collate(None)
            assert out["emb"].shape == (96,) + SHAPE  # declared shape
            for col in out.values():
                assert col.ctypes.data % 64 == 0  # aligned_empty output


# ---------------------------------------------------------------- replay


class TestReplayCache:
    def test_epoch2_byte_identical_to_epoch1(self, tensor_lsf_table):
        it = tensor_lsf_table.scan().batch_size(256).to_jax_iter(cache="device")
        e1 = read_epoch(it)
        st = it.stats()["replay"]
        assert st["ready"] and not st["spilled"]
        assert st["resident_rows"] == 2048 and st["resident_batches"] == 8
        e2 = read_epoch(it)
        assert_epochs_byte_identical(e1, e2)
        assert e2[0]["emb"].shape == (256,) + SHAPE  # declared shape on device
        # epoch 3 still replays (and still matches)
        assert_epochs_byte_identical(e1, read_epoch(it))

    def test_budget_overflow_spills_typed_and_metered(self, tensor_lsf_table):
        from lakesoul_tpu.obs import registry

        per_batch = 256 * (WIDTH * 4 + 4 + 4)  # f32 emb + demoted id + label
        spill_before = registry().counter(
            "lakesoul_replay_spilled_batches_total"
        ).value
        it = tensor_lsf_table.scan().batch_size(256).to_jax_iter(
            cache="device", replay_budget_bytes=3 * per_batch + 64
        )
        e1 = read_epoch(it)
        st = it.stats()["replay"]
        assert st["spilled"] and st["ready"]
        assert 1 <= st["resident_batches"] < 8
        assert st["resident_rows"] == st["resident_batches"] * 256
        assert st["resident_bytes"] <= 3 * per_batch + 64
        spill = it._replay.spill
        assert spill.budget_bytes == 3 * per_batch + 64
        assert spill.resident_batches == st["resident_batches"]
        assert registry().counter(
            "lakesoul_replay_spilled_batches_total"
        ).value > spill_before
        # the hybrid epoch — resident prefix from device + re-streamed tail —
        # is byte-identical to the streamed epoch, twice
        assert_epochs_byte_identical(e1, read_epoch(it))
        assert_epochs_byte_identical(e1, read_epoch(it))

    def test_abandoned_epoch_leaves_cache_unfilled(self, tensor_lsf_table):
        it = tensor_lsf_table.scan().batch_size(256).to_jax_iter(cache="device")
        for _ in it:
            break  # abandon: partial replay would silently drop data
        assert not it._replay.ready and it._replay.resident_batches == 0
        assert len(read_epoch(it)) == 8  # next pass streams and completes

    def test_permutation_deterministic_under_pinned_seed(self, tensor_lsf_table):
        def replayed(seed):
            it = tensor_lsf_table.scan().batch_size(256).to_jax_iter(
                cache="device", replay_permute=True, replay_seed=seed
            )
            list(it)  # epoch 1 fills
            return read_epoch(it), it

        a, it_a = replayed(7)
        b, _ = replayed(7)
        assert_epochs_byte_identical(a, b)  # same seed → identical epoch 2
        ids = np.concatenate([x["id"] for x in a])
        assert not np.array_equal(ids, np.arange(2048))  # actually permuted
        assert np.array_equal(np.sort(ids), np.arange(2048))  # nothing lost
        # next epoch of the SAME iterator draws a different permutation...
        c = read_epoch(it_a)
        ids_c = np.concatenate([x["id"] for x in c])
        assert not np.array_equal(ids_c, ids)
        assert np.array_equal(np.sort(ids_c), np.arange(2048))
        # ...and a different seed differs from epoch 2 of seed 7
        d, _ = replayed(8)
        ids_d = np.concatenate([x["id"] for x in d])
        assert not np.array_equal(ids_d, ids)

    def test_spilled_cache_replays_in_stream_order(self, tensor_lsf_table):
        per_batch = 256 * (WIDTH * 4 + 4 + 4)
        it = tensor_lsf_table.scan().batch_size(256).to_jax_iter(
            cache="device", replay_permute=True, replay_seed=1,
            replay_budget_bytes=2 * per_batch + 64,
        )
        e1 = read_epoch(it)
        assert it.stats()["replay"]["spilled"]
        # permutation is NOT honoured while spilled: the hybrid epoch must
        # stay position-exact against the streamed tail
        assert_epochs_byte_identical(e1, read_epoch(it))

    def test_env_budget_and_bad_values(self, tensor_lsf_table, monkeypatch):
        per_batch = 256 * (WIDTH * 4 + 4 + 4)
        monkeypatch.setenv("LAKESOUL_REPLAY_BUDGET_BYTES", str(2 * per_batch + 64))
        it = tensor_lsf_table.scan().batch_size(256).to_jax_iter(cache="device")
        list(it)
        assert it.stats()["replay"]["spilled"]
        assert it.stats()["replay"]["resident_batches"] == 2
        monkeypatch.setenv("LAKESOUL_REPLAY_BUDGET_BYTES", "not-a-number")
        with pytest.raises(ConfigError):
            tensor_lsf_table.scan().to_jax_iter(cache="device")

    def test_interleaved_iterations_share_cache_safely(self, tensor_lsf_table):
        """Two concurrently-active iterations of ONE cache='device' loader:
        only the first claims the fill, so the sealed epoch holds each
        batch exactly once (no doubled replay, no offer-after-seal crash)
        and both streams deliver the full table."""
        it = tensor_lsf_table.scan().batch_size(256).to_jax_iter(cache="device")
        a, b = iter(it), iter(it)
        rows_a = rows_b = 0
        for x, y in zip(a, b):  # fully interleaved to completion
            rows_a += x["id"].shape[0]
            rows_b += y["id"].shape[0]
        assert rows_a == rows_b == 2048
        st = it.stats()["replay"]
        assert st["ready"]
        assert st["resident_rows"] == 2048 and st["resident_batches"] == 8
        replay = read_epoch(it)
        assert len(replay) == 8  # not 16: the epoch was sealed ONCE
        ids = np.concatenate([x["id"] for x in replay])
        assert np.array_equal(np.sort(ids), np.arange(2048))
        # partial-then-finish interleave: the survivor must not crash on a
        # sealed cache either
        it2 = tensor_lsf_table.scan().batch_size(256).to_jax_iter(cache="device")
        g1, g2 = iter(it2), iter(it2)
        next(g1)
        consumed = 1 + sum(1 for _ in g2)  # g2 (non-owner) runs to the end
        assert consumed == 9
        rest = sum(1 for _ in g1)  # owner finishes afterwards and seals
        assert rest == 7
        assert it2.stats()["replay"]["resident_batches"] == 8

    def test_replay_kwargs_without_cache_typed(self, tensor_lsf_table):
        scan = tensor_lsf_table.scan()
        with pytest.raises(ConfigError, match="cache='device'"):
            scan.to_jax_iter(replay_permute=True)
        with pytest.raises(ConfigError, match="cache='device'"):
            scan.to_jax_iter(replay_budget_bytes=1 << 20)
        with pytest.raises(ConfigError, match="cache='device'"):
            scan.to_jax_iter(replay_seed=7)

    def test_every_refused_offer_is_metered(self):
        from lakesoul_tpu.obs import registry

        counter = registry().counter("lakesoul_replay_spilled_batches_total")
        before = counter.value
        cache = DeviceReplayCache(budget_bytes=1024)
        batch = deliver({"x": aligned_empty((64, 4), np.float32)})  # 1 KiB
        assert cache.offer(64, batch)
        for _ in range(5):  # the crossing offer + 4 more refusals
            assert not cache.offer(64, batch)
        assert counter.value - before == 5

    def test_cache_state_machine_misuse_typed(self):
        cache = DeviceReplayCache(budget_bytes=1 << 20)
        with pytest.raises(ConfigError):
            list(cache.replay())  # before seal
        cache.seal()
        with pytest.raises(ConfigError):
            cache.offer(1, {"x": np.zeros(1, np.float32)})  # after seal
        with pytest.raises(ConfigError):
            DeviceReplayCache(budget_bytes=0)

    def test_batch_bills_per_device_shard_bytes(self):
        """Residency accounting bills what ONE device actually holds — the
        leaf's shard shape.  On this 1-device CI the shard IS the leaf; the
        replicated case (each device holds the FULL array) is pinned via
        an explicit single-device sharding, which is replication's shape."""
        import jax

        from lakesoul_tpu.tensorplane.replay import _batch_device_bytes

        out = deliver({"x": aligned_empty((64, 8), np.float32)})
        shard = out["x"].sharding.shard_shape(out["x"].shape)
        assert _batch_device_bytes(out) == int(np.prod(shard)) * 4
        # a replicated leaf must bill its FULL bytes per device — never
        # nbytes / ndev (that under-bills by the replication factor)
        replicated = jax.device_put(np.zeros((64, 8), np.float32))
        assert _batch_device_bytes({"x": replicated}) == replicated.nbytes
        # host arrays (no sharding) bill conservatively at full size
        assert _batch_device_bytes({"x": np.zeros((4, 4), np.float32)}) == 64


# ----------------------------------------------------------------- smoke


class TestTpuSmoke:
    def test_register_covers_every_pallas_kernel(self):
        """The acceptance criterion: the smoke register covers 100% of the
        Pallas kernels lakelint's device index enumerates — a new kernel
        cannot land without joining the register."""
        from lakesoul_tpu.tensorplane.smoke import (
            enumerate_pallas_kernels,
            smoke_cases,
        )

        enumerated = set(enumerate_pallas_kernels())
        assert enumerated, "device index found no Pallas kernels?"
        covered = {k for c in smoke_cases() for k in c.kernels}
        assert enumerated - covered == set(), (
            "Pallas kernels missing from the smoke register"
        )

    def test_cpu_fallback_report_is_complete(self):
        """On CPU fallback every kernel still differential-tests in
        interpret mode and the report records EVERY on-chip claim in
        untested_on_tpu — the live-tunnel to-do list."""
        from lakesoul_tpu.tensorplane.smoke import run_smoke, smoke_cases

        report = run_smoke()
        assert report["ok"], report
        assert not report["on_tpu"]
        assert report["untested_on_tpu"] == [c.name for c in smoke_cases()]
        by_name = {c["name"]: c for c in report["cases"]}
        for case in smoke_cases():
            entry = by_name[case.name]
            if case.min_devices > report["device_count"] or case.heavy:
                assert entry["status"] == "skipped"
            else:
                assert entry["status"] == "cpu_fallback_pass", entry
                assert entry["seconds"] >= 0
        assert report["kernel_enumeration"]["uncovered"] == []

    def test_smoke_cli_exit_contract(self, capsys):
        import importlib.util
        import pathlib

        root = pathlib.Path(__file__).resolve().parents[1]
        spec = importlib.util.spec_from_file_location(
            "_tpu_smoke_cli", root / "tools" / "tpu_smoke.py"
        )
        mod = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(mod)
        assert mod.main([]) == 0
        out = capsys.readouterr().out
        import json

        report = json.loads(out)
        assert report["ok"] and report["untested_on_tpu"]
