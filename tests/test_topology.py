"""Process-level chaos for the multi-process topology (PR 7 tentpole).

The acceptance contract, proven with real OS processes sharing one
warehouse (file-backed SQLite metadata — the deployment shape):

- SIGKILL a leased compactor mid-job → a second service process completes
  the partition within one lease TTL, with ZERO double-compactions
  (asserted via the fencing-token trail in commit history) and no lost
  trigger events (every gap-crossing partition still gets compacted —
  the polling watermark re-derives candidates from committed state).
- Two compaction service processes racing a writer process drain through
  the PR-6 conflict-retry path and leave table state byte-identical to a
  single-process run of the same commit sequence.

The killed child is the REAL service entry point
(``python -m lakesoul_tpu.compaction``), not a test harness double."""

from __future__ import annotations

import os
import pathlib
import signal
import subprocess
import sys
import textwrap
import time

import numpy as np
import pyarrow as pa
import pytest

from lakesoul_tpu import LakeSoulCatalog
from lakesoul_tpu.compaction.service import LeasedCompactionService
from lakesoul_tpu.meta.entity import CommitOp

REPO = str(pathlib.Path(__file__).resolve().parent.parent)
SCHEMA = pa.schema([("id", pa.int64()), ("v", pa.float64()), ("p", pa.string())])
TTL_S = 2.0


def _child_env(**extra) -> dict:
    env = dict(os.environ)
    env.update({
        "JAX_PLATFORMS": "cpu",
        "PYTHONPATH": REPO,
        "LAKESOUL_RETRY_SEED": "7",  # reproducible backoff schedules
    })
    env.update(extra)
    return env


def _spawn_compactor(wh: str, db: str, *, service_id: str, **env) -> subprocess.Popen:
    return subprocess.Popen(
        [
            sys.executable, "-m", "lakesoul_tpu.compaction",
            "--warehouse", wh, "--db-path", db,
            "--lease-ttl-s", str(TTL_S), "--poll-s", "0.1",
            "--service-id", service_id,
        ],
        env=_child_env(**env),
        stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True, cwd=REPO,
    )


def _stack(t, part: str, n: int, *, base: float = 0.0):
    for i in range(n):
        t.upsert(pa.table({
            "id": np.arange(8, dtype=np.int64),
            "v": np.full(8, base + i),
            "p": np.repeat(part, 8),
        }, schema=SCHEMA))


def _compaction_versions(store, table_id: str, desc: str):
    return [
        v for v in store.get_partition_versions(table_id, desc)
        if v.commit_op == CommitOp.COMPACTION
    ]


class TestSigkillTakeover:
    def test_peer_finishes_within_one_ttl_no_double_compaction(self, tmp_path):
        wh, db = str(tmp_path / "wh"), str(tmp_path / "meta.db")
        catalog = LakeSoulCatalog(wh, db_path=db)
        t = catalog.create_table(
            "t", SCHEMA, primary_keys=["id"], range_partitions=["p"],
            hash_bucket_num=1,
        )
        _stack(t, "a", 12)
        _stack(t, "b", 12, base=100.0)
        store = catalog.client.store
        assert len(store.get_compaction_candidates()) == 2
        before = t.to_arrow().sort_by([("p", "ascending"), ("id", "ascending")])

        # child service: hangs inside its first leased job (holding the
        # lease), exactly where a SIGKILL is most destructive
        proc = _spawn_compactor(
            wh, db, service_id="victim",
            LAKESOUL_FAULTS="compaction.leased_job:1:hang:300",
        )
        keys = [f"compaction/{t.info.table_id}/p=a",
                f"compaction/{t.info.table_id}/p=b"]
        held_key = None
        try:
            deadline = time.monotonic() + 60.0
            while time.monotonic() < deadline:
                for k in keys:
                    lease = store.get_lease(k)
                    if lease is not None:
                        held_key = k
                        assert lease.holder == "victim"
                        assert lease.fencing_token == 1
                        break
                if held_key or proc.poll() is not None:
                    break
                time.sleep(0.05)
            if not held_key:
                proc.kill()
                _, err = proc.communicate(timeout=10.0)
                pytest.fail(f"victim never took a lease: {err}")
        finally:
            proc.send_signal(signal.SIGKILL)
            proc.wait(10.0)
        killed_at = time.monotonic()
        held_desc = held_key.rsplit("/", 1)[-1]

        # peer service (this process): must pick up BOTH partitions — the
        # free one immediately, the victim's within one TTL of the kill
        peer = LeasedCompactionService(
            catalog, service_id="peer", lease_ttl_s=TTL_S, poll_interval_s=0.1,
        )
        victim_partition_done_at = None
        deadline = time.monotonic() + 30.0
        while time.monotonic() < deadline:
            peer.poll_once()
            if victim_partition_done_at is None and _compaction_versions(
                store, t.info.table_id, held_desc
            ):
                victim_partition_done_at = time.monotonic()
            if not store.get_compaction_candidates():
                break
            time.sleep(0.05)

        # no lost trigger events: every gap-crossing partition compacted
        assert store.get_compaction_candidates() == []
        assert victim_partition_done_at is not None
        takeover_latency = victim_partition_done_at - killed_at
        # "within one lease TTL": expiry is ≤ TTL after the kill; poll
        # cadence + the compact itself add the small remainder
        assert takeover_latency < TTL_S + 4.0, takeover_latency
        assert peer.stats.takeovers >= 1

        # ZERO double-compaction, via the fencing trail: exactly one
        # CompactionCommit per partition; the victim's partition carries
        # the TAKEOVER token (2), the free one the first-acquire token (1)
        for desc in ("p=a", "p=b"):
            compactions = _compaction_versions(store, t.info.table_id, desc)
            assert len(compactions) == 1, (desc, compactions)
        fences = {
            desc: _compaction_versions(store, t.info.table_id, desc)[0].expression
            for desc in ("p=a", "p=b")
        }
        other_desc = next(d for d in ("p=a", "p=b") if d != held_desc)
        assert fences[held_desc] == "fence=2"
        assert fences[other_desc] == "fence=1"

        # the victim left no half-commit debris, and data is untouched
        assert store.list_uncommitted_commits() == []
        after = (
            t.refresh().to_arrow()
            .sort_by([("p", "ascending"), ("id", "ascending")])
        )
        assert after.equals(before)


_WRITER_SCRIPT = textwrap.dedent(
    """
    import sys
    import numpy as np, pyarrow as pa
    from lakesoul_tpu import LakeSoulCatalog

    wh, db = sys.argv[1], sys.argv[2]
    SCHEMA = pa.schema([("id", pa.int64()), ("v", pa.float64()), ("p", pa.string())])
    t = LakeSoulCatalog(wh, db_path=db).table("t")
    for i in range(14):
        for part, base in (("a", 0.0), ("b", 100.0)):
            t.upsert(pa.table({
                "id": np.arange(8, dtype=np.int64),
                "v": np.full(8, base + i),
                "p": np.repeat(part, 8),
            }, schema=SCHEMA))
    print("WROTE", flush=True)
    """
)


class TestTwoServicesRaceWriter:
    def _run_writer_inline(self, t):
        for i in range(14):
            for part, base in (("a", 0.0), ("b", 100.0)):
                t.upsert(pa.table({
                    "id": np.arange(8, dtype=np.int64),
                    "v": np.full(8, base + i),
                    "p": np.repeat(part, 8),
                }, schema=SCHEMA))

    def _sorted_ipc(self, table: pa.Table) -> bytes:
        import io

        out = (
            table
            .sort_by([("p", "ascending"), ("id", "ascending")])
            .combine_chunks()
        )
        sink = io.BytesIO()
        with pa.ipc.new_stream(sink, out.schema) as w:
            w.write_table(out)
        return sink.getvalue()

    def test_race_drains_byte_identical_to_single_process(self, tmp_path):
        # ---- baseline: one process, writer then a single service
        wh1 = str(tmp_path / "wh1")
        cat1 = LakeSoulCatalog(wh1, db_path=str(tmp_path / "m1.db"))
        t1 = cat1.create_table(
            "t", SCHEMA, primary_keys=["id"], range_partitions=["p"],
            hash_bucket_num=1,
        )
        self._run_writer_inline(t1)
        svc = LeasedCompactionService(cat1, lease_ttl_s=30, poll_interval_s=0.01)
        for _ in range(10):
            if not cat1.client.store.get_compaction_candidates():
                break
            svc.poll_once()
        baseline = self._sorted_ipc(t1.refresh().to_arrow())

        # ---- race: a writer PROCESS racing two service PROCESSES
        wh2, db2 = str(tmp_path / "wh2"), str(tmp_path / "m2.db")
        cat2 = LakeSoulCatalog(wh2, db_path=db2)
        t2 = cat2.create_table(
            "t", SCHEMA, primary_keys=["id"], range_partitions=["p"],
            hash_bucket_num=1,
        )
        services = [
            _spawn_compactor(wh2, db2, service_id=f"svc{i}") for i in (1, 2)
        ]
        try:
            writer = subprocess.run(
                [sys.executable, "-c", _WRITER_SCRIPT, wh2, db2],
                env=_child_env(), capture_output=True, text=True,
                timeout=240, cwd=REPO,
            )
            assert writer.returncode == 0, writer.stderr[-2000:]
            # conflict-retry really ran on the writer side of the race iff
            # the services landed compactions while it was committing; the
            # store-level proof is below (interleaved commit history)
            deadline = time.monotonic() + 60.0
            store = cat2.client.store
            while time.monotonic() < deadline:
                if not store.get_compaction_candidates():
                    break
                time.sleep(0.2)
            assert store.get_compaction_candidates() == [], "gaps never drained"
        finally:
            for p in services:
                p.terminate()
            for p in services:
                try:
                    p.wait(10.0)
                except subprocess.TimeoutExpired:
                    p.kill()

        store = cat2.client.store
        # the services really compacted — and did so AGAINST the live
        # writer: at least one compaction is not the final version, i.e.
        # writer commits stacked on top of it through conflict-retry
        compactions = []
        for desc in ("p=a", "p=b"):
            versions = store.get_partition_versions(t2.info.table_id, desc)
            c = [v for v in versions if v.commit_op == CommitOp.COMPACTION]
            assert c, f"{desc} never compacted"
            compactions.append((c, versions[-1]))
        # no half-commits anywhere after the race
        assert store.list_uncommitted_commits() == []
        # every compaction commit carries its lease's fencing stamp
        for c, _head in compactions:
            for v in c:
                assert v.expression.startswith("fence="), v

        raced = self._sorted_ipc(t2.refresh().to_arrow())
        assert raced == baseline, "race run diverged from single-process state"
