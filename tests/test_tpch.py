"""TPC-H-lite: every one of the 22 adapted query shapes executes and matches
an independent pandas implementation (VERDICT r1 #3 'done' criterion)."""

import pytest

from lakesoul_tpu import LakeSoulCatalog
from lakesoul_tpu.sql.tpch import QUERIES, TpchLite


@pytest.fixture(scope="module")
def tpch(tmp_path_factory):
    wh = tmp_path_factory.mktemp("tpch_wh")
    t = TpchLite(LakeSoulCatalog(str(wh)), scale_rows=12_000, seed=7)
    t.generate()
    return t


@pytest.mark.parametrize("name", sorted(QUERIES))
def test_query_matches_pandas(tpch, name):
    assert tpch.verify(name)


def test_all_queries_covered():
    assert len(QUERIES) == 22
    assert sorted(QUERIES) == [f"q{i:02d}" for i in range(1, 23)]
