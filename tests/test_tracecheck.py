"""tracecheck: the runtime retrace detector must count distinct abstract
signatures per jit entry, trip the budget on shape-thrashing call patterns,
stay silent on bucketed/stable ones, instrument and cleanly restore both
future jit wrappings and already-imported hot modules, and never record
trace-time (jit-of-jit) calls as top-level compilations."""

from __future__ import annotations

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from lakesoul_tpu.analysis import tracecheck


@pytest.fixture()
def armed():
    tracecheck.reset()
    tracecheck.enable()
    yield
    tracecheck.disable()
    tracecheck.reset()


def test_shape_thrash_trips_budget(armed):
    @jax.jit
    def f(x):
        return x * 2

    label = f"{__name__}.test_shape_thrash_trips_budget.<locals>.f"
    tracecheck.set_budget(label, 3)
    for n in range(1, 7):  # 6 distinct shapes against a budget of 3
        f(np.ones(n, np.float32))
    violations = tracecheck.violations()
    assert len(violations) == 1
    v = violations[0]
    assert v.kind == "retrace-budget"
    assert v.function == label
    assert v.count == 6 and v.budget == 3
    # the violation names the thrashing shapes so the fix is obvious
    assert "float32[1]" in v.render() and "float32[6]" in v.render()


def test_stable_and_bucketed_shapes_stay_clean(armed):
    @jax.jit
    def g(x):
        return x + 1

    tracecheck.set_budget(f"{__name__}.test_stable_and_bucketed_shapes_stay_clean.<locals>.g", 2)
    for _ in range(10):
        g(np.ones(8, np.float32))  # same signature every time
    g(np.ones(16, np.float32))  # one pow2 bucket up: still within budget
    assert tracecheck.violations() == []
    counts = tracecheck.signature_counts()
    (label,) = [k for k in counts if k.endswith(".g")]
    assert counts[label] == 2


def test_static_arg_change_counts_as_retrace(armed):
    import functools

    @functools.partial(jax.jit, static_argnames=("k",))
    def h(x, *, k):
        return x[:k]

    label = [k for k in [f"{__name__}.test_static_arg_change_counts_as_retrace.<locals>.h"]][0]
    tracecheck.set_budget(label, 2)
    for k in range(1, 5):
        h(np.ones(8, np.float32), k=k)  # every k re-specializes
    (v,) = tracecheck.violations()
    assert v.count == 4


def test_trace_time_inner_calls_not_counted(armed):
    @jax.jit
    def inner(x):
        return x * 3

    @jax.jit
    def outer(x):
        return inner(x) + 1  # traced call: inlined, no top-level compile

    outer(np.ones(4, np.float32))
    counts = tracecheck.signature_counts()
    assert any(k.endswith(".outer") for k in counts)
    assert not any(k.endswith(".inner") for k in counts)


def test_hot_module_instrumented_and_restored():
    import lakesoul_tpu.vector.kernels as kernels

    orig = kernels.packed_dot_pallas
    tracecheck.reset()
    tracecheck.enable()
    try:
        assert isinstance(
            kernels.packed_dot_pallas, tracecheck._TraceCheckedFn
        )
        # the jnp fallback path drives the jitted estimator end to end
        codes = np.random.default_rng(0).integers(
            0, 255, (100, 8), dtype=np.uint8
        )
        out = kernels.packed_scan(
            codes, np.ones(100, np.float32), np.ones(100, np.float32),
            np.ones(64, np.float32), d=64, pallas=False,
        )
        assert out.shape == (100,)
        assert any(
            "estimate_distances" in k for k in tracecheck.signature_counts()
        )
    finally:
        tracecheck.disable()
        tracecheck.reset()
    assert kernels.packed_dot_pallas is orig  # restored exactly


def test_jit_patch_restored_and_aot_surface_passthrough():
    real_jit = jax.jit
    tracecheck.reset()
    tracecheck.enable()
    try:
        @jax.jit
        def f(x):
            return x - 1

        # AOT/introspection surfaces must keep working on the proxy
        assert f.lower(np.ones(3, np.float32)) is not None
        f(np.ones(3, np.float32))
    finally:
        tracecheck.disable()
        tracecheck.reset()
    assert jax.jit is real_jit


def test_watch_scopes_violations():
    tracecheck.reset()
    with tracecheck.watch() as w:
        @jax.jit
        def f(x):
            return x

        tracecheck.set_budget(
            f"{__name__}.test_watch_scopes_violations.<locals>.f", 1
        )
        f(np.ones(2, np.float32))
        f(np.ones(3, np.float32))
    assert len(w.violations) == 1
    assert not tracecheck.enabled()
    tracecheck.reset()


def test_env_gate():
    assert tracecheck.env_requested() in (True, False)
    # the conftest fixture only arms when LAKESOUL_TRACECHECK=1; the
    # detector itself never auto-enables on import
    assert not tracecheck.enabled() or tracecheck.env_requested()
