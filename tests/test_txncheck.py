"""Transaction-interleaving replay (analysis/txncheck): the real lease
protocol — acquire, holder refresh, expiry takeover, renew, tombstone
release — must replay clean under READ COMMITTED interleavings on both
backends, while a seeded unlocked read-then-blind-write is caught as a
lost update with both transactions' statement stacks and the offending
interleaving, and a regressing fencing token is caught per key.  Also
pins the detector's control surface (env gate, enable/disable restoring
the store seams, aborted transactions recording nothing, autocommit
reads staying untraced, idempotent replay)."""

import sys
import threading

import pytest

import fake_psycopg2

from lakesoul_tpu.analysis import txncheck
from lakesoul_tpu.meta.store import SqliteMetadataStore, SqlMetadataStore


@pytest.fixture(autouse=True)
def _pristine_detector():
    """Every test starts and ends with the real store seams."""
    assert not txncheck.enabled()
    yield
    txncheck.disable()
    txncheck.reset()


@pytest.fixture()
def store(tmp_path):
    return SqliteMetadataStore(str(tmp_path / "meta.db"))


# ------------------------------------------------------------ control plane


def test_env_gate(monkeypatch):
    monkeypatch.delenv("LAKESOUL_TXNCHECK", raising=False)
    assert not txncheck.env_requested()
    monkeypatch.setenv("LAKESOUL_TXNCHECK", "1")
    assert txncheck.env_requested()
    monkeypatch.setenv("LAKESOUL_TXNCHECK", "0")
    assert not txncheck.env_requested()


def test_enable_disable_restores_seams():
    real_exec = SqlMetadataStore.__dict__["_exec"]
    real_base_txn = SqlMetadataStore.__dict__["transaction"]
    real_sqlite_txn = SqliteMetadataStore.__dict__["transaction"]
    txncheck.enable()
    txncheck.enable()  # idempotent
    assert SqlMetadataStore.__dict__["_exec"] is not real_exec
    assert SqliteMetadataStore.__dict__["transaction"] is not real_sqlite_txn
    txncheck.disable()
    txncheck.disable()
    assert SqlMetadataStore.__dict__["_exec"] is real_exec
    assert SqlMetadataStore.__dict__["transaction"] is real_base_txn
    assert SqliteMetadataStore.__dict__["transaction"] is real_sqlite_txn


def test_autocommit_reads_stay_untraced(store):
    with txncheck.watch():
        assert store.get_lease("nobody-here") is None
        assert txncheck.transactions() == []


def test_aborted_transaction_records_nothing(store):
    with txncheck.watch():
        with pytest.raises(RuntimeError):
            with store.transaction() as conn:
                store._exec(
                    conn, "UPDATE global_config SET value=? WHERE key=?",
                    ("v", "k"),
                )
                raise RuntimeError("abort before commit")
        assert txncheck.transactions() == []
        assert txncheck.replay() == []


# ------------------------------------------------- real protocols stay clean


def test_lease_protocol_replays_clean(store):
    with txncheck.watch():
        def contender(name):
            got = store.acquire_lease("part-1", name, ttl_ms=60_000)
            if got:
                assert store.renew_lease(
                    "part-1", name, got.fencing_token, ttl_ms=60_000
                )
                assert store.release_lease("part-1", name, got.fencing_token)

        threads = [
            threading.Thread(target=contender, args=(f"h{i}",), name=f"h{i}")
            for i in range(4)
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        assert txncheck.transactions(), "the seam was not traced"
        assert txncheck.replay() == []


def test_expiry_takeover_replays_clean(store):
    with txncheck.watch():
        first = store.acquire_lease("p", "a", ttl_ms=10, now_ms=1_000)
        assert first is not None and first.fencing_token == 1
        took = store.acquire_lease("p", "b", ttl_ms=10_000, now_ms=5_000)
        assert took is not None and took.fencing_token == 2 and took.taken_over
        assert store.release_lease("p", "b", took.fencing_token)
        # a released tombstone re-acquires with the NEXT token — the
        # sequence stays monotonic for the table's lifetime
        again = store.acquire_lease("p", "c", ttl_ms=10_000, now_ms=6_000)
        assert again is not None and again.fencing_token == 3
        assert txncheck.replay() == []


def test_pg_store_lease_protocol_replays_clean(tmp_path, monkeypatch):
    monkeypatch.setitem(sys.modules, "psycopg2", fake_psycopg2)
    from lakesoul_tpu.meta.store import PostgresMetadataStore

    dsn = f"postgresql://fake/{tmp_path.name}-txncheck"
    store = PostgresMetadataStore(dsn)
    try:
        with txncheck.watch():
            got = store.acquire_lease("part-7", "pg-holder", ttl_ms=60_000)
            assert got is not None
            assert store.renew_lease(
                "part-7", "pg-holder", got.fencing_token, ttl_ms=60_000
            )
            assert store.release_lease("part-7", "pg-holder", got.fencing_token)
            assert txncheck.transactions(), "the PG seam was not traced"
            assert txncheck.replay() == []
    finally:
        fake_psycopg2.reset(dsn)


def test_cas_guarded_write_replays_clean(store):
    """A write whose WHERE re-checks a column the peer wrote survives the
    interleaving: the peer's commit makes it match zero rows."""
    store.acquire_lease("part-9", "holder", ttl_ms=60_000)
    with txncheck.watch():
        def holder():
            with store.transaction() as conn:
                row = store._exec(
                    conn,
                    "SELECT fencing_token FROM lease WHERE lease_key=?",
                    ("part-9",),
                ).fetchone()
                store._exec(
                    conn,
                    "UPDATE lease SET expires_at_ms=?"
                    " WHERE lease_key=? AND fencing_token=?",
                    (999, "part-9", row[0]),
                )

        t = threading.Thread(target=holder, name="holder-thread")
        t.start()
        t.join()
        with store.transaction() as conn:
            store._exec(
                conn,
                "UPDATE lease SET expires_at_ms=?, fencing_token=?"
                " WHERE lease_key=?",
                (111, 7, "part-9"),
            )
        assert txncheck.replay() == []


# ------------------------------------------------------ seeded bugs caught


def test_unlocked_read_then_blind_write_caught(store):
    store.acquire_lease("part-9", "victim", ttl_ms=60_000)
    with txncheck.watch() as w:
        def victim():
            with store.transaction() as conn:
                store._exec(
                    conn,
                    "SELECT expires_at_ms FROM lease WHERE lease_key=?",
                    ("part-9",),
                ).fetchone()
                store._exec(
                    conn,
                    "UPDATE lease SET expires_at_ms=? WHERE lease_key=?",
                    (999, "part-9"),
                )

        t = threading.Thread(target=victim, name="victim-thread")
        t.start()
        t.join()
        with store.transaction() as conn:
            store._exec(
                conn,
                "UPDATE lease SET expires_at_ms=?, holder_id=?"
                " WHERE lease_key=?",
                (111, "thief", "part-9"),
            )
        found = txncheck.replay()
        assert [v.kind for v in found] == ["lost-update"]
        assert found == w.violations
    rendered = found[0].render()
    assert "Offending interleaving" in rendered
    assert "victim-thread" in rendered
    assert "lease[lease_key='part-9']" in rendered
    # both transactions' statement stacks ride along: read, write, peer
    assert len(found[0].stacks) == 3
    assert "test_txncheck.py" in found[0].stacks[0]
    # replay is idempotent over the same history
    assert txncheck.replay() == []


def test_fencing_regression_caught(store):
    with txncheck.watch():
        with store.transaction() as conn:
            store._exec(
                conn,
                "INSERT INTO lease(lease_key, holder_id, fencing_token,"
                " expires_at_ms, acquired_at_ms) VALUES (?,?,?,?,?)",
                ("k", "a", 5, 10, 1),
            )
        with store.transaction() as conn:
            store._exec(
                conn,
                "UPDATE lease SET fencing_token=?, holder_id=?"
                " WHERE lease_key=?",
                (3, "b", "k"),
            )
        found = txncheck.replay()
    assert [v.kind for v in found] == ["fencing-regression"]
    assert "5 -> 3" in found[0].message


def test_fencing_sequence_resets_after_delete(store):
    """DELETE ends the row's history (clean_all_for_test): a fresh token 1
    afterwards is a new sequence, not a regression."""
    with txncheck.watch():
        with store.transaction() as conn:
            store._exec(
                conn,
                "INSERT INTO lease(lease_key, holder_id, fencing_token,"
                " expires_at_ms, acquired_at_ms) VALUES (?,?,?,?,?)",
                ("k", "a", 5, 10, 1),
            )
        store.clean_all_for_test()
        with store.transaction() as conn:
            store._exec(
                conn,
                "INSERT INTO lease(lease_key, holder_id, fencing_token,"
                " expires_at_ms, acquired_at_ms) VALUES (?,?,?,?,?)",
                ("k", "b", 1, 20, 2),
            )
        assert txncheck.replay() == []
