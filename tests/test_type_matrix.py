"""Arrow type-matrix coverage: exotic logical types through the FULL path —
writer (partition/sort/flush) → physical format (parquet AND lsf) → MOR merge
→ scan — plus SQL comparisons over them.  The reference inherits this matrix
from parquet/arrow-rs (file_format.rs CanCastSchemaBuilder); here each leg is
pinned explicitly."""

import datetime
import decimal

import numpy as np
import pyarrow as pa
import pyarrow.compute as pc
import pytest

from lakesoul_tpu import LakeSoulCatalog


@pytest.fixture
def catalog(tmp_path):
    return LakeSoulCatalog(str(tmp_path / "wh"), db_path=str(tmp_path / "meta.db"))


def _mk(catalog, name, schema, fmt, **kw):
    props = dict(kw.pop("properties", {}))
    if fmt == "lsf":
        props["lakesoul.file_format"] = "lsf"
    return catalog.create_table(name, schema, properties=props, **kw)


PK_CASES = {
    "string": (pa.string(), lambda n: pa.array([f"k{i:06d}" for i in range(n)])),
    "timestamp": (
        pa.timestamp("us"),
        lambda n: pa.array(
            [datetime.datetime(2026, 1, 1) + datetime.timedelta(seconds=i) for i in range(n)],
            type=pa.timestamp("us"),
        ),
    ),
    "decimal": (
        pa.decimal128(12, 2),
        lambda n: pa.array([decimal.Decimal(i) / 100 for i in range(n)], type=pa.decimal128(12, 2)),
    ),
    "date": (
        pa.date32(),
        lambda n: pa.array(
            [datetime.date(2026, 1, 1) + datetime.timedelta(days=i) for i in range(n)]
        ),
    ),
    "binary": (pa.binary(), lambda n: pa.array([b"%06d" % i for i in range(n)])),
}


@pytest.mark.parametrize("fmt", ["parquet", "lsf"])
@pytest.mark.parametrize("pk_kind", sorted(PK_CASES))
def test_exotic_pk_upsert_mor(catalog, fmt, pk_kind):
    """Upserts on a non-int64 primary key must dedup correctly through MOR."""
    pk_type, gen = PK_CASES[pk_kind]
    n = 300
    schema = pa.schema([("k", pk_type), ("v", pa.int64())])
    t = _mk(catalog, f"pk_{pk_kind}_{fmt}", schema, fmt, primary_keys=["k"])
    keys = gen(n)
    t.write_arrow(pa.table({"k": keys, "v": np.arange(n)}))
    # overwrite every third key with v+1000
    idx = list(range(0, n, 3))
    t.upsert(pa.table({"k": keys.take(idx), "v": np.array(idx) + 1000}))
    out = t.scan().to_arrow().sort_by("v")
    assert out.num_rows == n
    got = dict(zip(out.column("k").to_pylist(), out.column("v").to_pylist()))
    expect = {keys[i].as_py(): (i + 1000 if i % 3 == 0 else i) for i in range(n)}
    assert got == expect


@pytest.mark.parametrize("fmt", ["parquet", "lsf"])
def test_nested_values_survive_mor(catalog, fmt):
    """list/struct/fixed_size_list/map value columns ride UseLast through an
    upsert wave in both physical formats."""
    schema = pa.schema(
        [
            ("id", pa.int64()),
            ("emb", pa.list_(pa.float32())),
            ("meta", pa.struct([("a", pa.int32()), ("b", pa.string())])),
            ("vec", pa.list_(pa.float32(), 4)),
            ("tags", pa.map_(pa.string(), pa.int32())),
        ]
    )
    t = _mk(catalog, f"nested_{fmt}", schema, fmt, primary_keys=["id"])
    n = 100

    def batch(ids, mark):
        return pa.table(
            {
                "id": pa.array(ids, type=pa.int64()),
                "emb": pa.array([[float(i), mark] for i in ids], type=pa.list_(pa.float32())),
                "meta": pa.array(
                    [{"a": i, "b": f"m{mark}"} for i in ids],
                    type=schema.field("meta").type,
                ),
                "vec": pa.array(
                    [[float(i)] * 4 for i in ids], type=pa.list_(pa.float32(), 4)
                ),
                "tags": pa.array(
                    [[(f"t{mark}", i)] for i in ids], type=schema.field("tags").type
                ),
            }
        )

    t.write_arrow(batch(list(range(n)), mark=0.0))
    t.upsert(batch(list(range(0, n, 2)), mark=1.0))
    out = t.scan().to_arrow().sort_by("id")
    assert out.num_rows == n
    embs = out.column("emb").to_pylist()
    metas = out.column("meta").to_pylist()
    tags = out.column("tags").to_pylist()
    for i in range(n):
        mark = 1.0 if i % 2 == 0 else 0.0
        assert embs[i] == [float(i), mark]
        assert metas[i] == {"a": i, "b": f"m{mark}"}
        assert tags[i] == [(f"t{mark}", i)]
    assert out.column("vec").to_pylist()[7] == [7.0] * 4


@pytest.mark.parametrize("fmt", ["parquet", "lsf"])
def test_temporal_and_decimal_values(catalog, fmt):
    """timestamp(tz)/duration/decimal value columns round-trip exactly."""
    tz = pa.timestamp("us", tz="UTC")
    schema = pa.schema(
        [
            ("id", pa.int64()),
            ("ts", tz),
            ("dur", pa.duration("ms")),
            ("amt", pa.decimal128(20, 4)),
            ("flag", pa.bool_()),
        ]
    )
    t = _mk(catalog, f"temporal_{fmt}", schema, fmt, primary_keys=["id"])
    n = 200
    base = datetime.datetime(2026, 7, 29, tzinfo=datetime.timezone.utc)
    tbl = pa.table(
        {
            "id": np.arange(n),
            "ts": pa.array([base + datetime.timedelta(minutes=i) for i in range(n)], type=tz),
            "dur": pa.array([datetime.timedelta(milliseconds=i * 7) for i in range(n)]),
            "amt": pa.array(
                [decimal.Decimal(i * i) / 10000 for i in range(n)], type=pa.decimal128(20, 4)
            ),
            "flag": pa.array([i % 3 == 0 for i in range(n)]),
        }
    )
    t.write_arrow(tbl)
    out = t.scan().to_arrow().sort_by("id")
    assert out.column("ts").to_pylist() == tbl.column("ts").to_pylist()
    assert out.column("dur").to_pylist() == tbl.column("dur").to_pylist()
    assert out.column("amt").to_pylist() == tbl.column("amt").to_pylist()
    assert out.column("flag").to_pylist() == tbl.column("flag").to_pylist()


def test_temporal_range_partition(catalog):
    """A date range-partition column partitions correctly and filters via the
    indexed point-lookup path."""
    schema = pa.schema([("id", pa.int64()), ("d", pa.date32()), ("v", pa.float64())])
    t = catalog.create_table("by_day", schema, primary_keys=["id"], range_partitions=["d"])
    d0, d1 = datetime.date(2026, 7, 1), datetime.date(2026, 7, 2)
    t.write_arrow(
        pa.table(
            {
                "id": np.arange(100),
                "d": pa.array([d0] * 50 + [d1] * 50),
                "v": np.ones(100),
            }
        )
    )
    only = t.scan().partitions({"d": str(d0)}).to_arrow()
    assert only.num_rows == 50
    assert set(only.column("d").to_pylist()) == {d0}


def test_filter_json_serde_exotic_values():
    """Temporal/decimal/bytes predicate values survive the JSON wire format
    (Flight tickets) via tagged encoding."""
    from lakesoul_tpu.io.filters import Filter, col

    vals = [
        datetime.datetime(2026, 7, 2, 12, 30, 0, 123456),
        datetime.date(2026, 7, 2),
        datetime.timedelta(milliseconds=1500),
        decimal.Decimal("12.3400"),
        b"\x00\xffkey",
    ]
    for v in vals:
        f = col("c") >= v
        rt = Filter.from_json(f.to_json())
        assert rt.value == v and type(rt.value) is type(v), v
    f = col("c").is_in([vals[0], vals[0] + datetime.timedelta(days=1)])
    rt = Filter.from_json(f.to_json())
    assert rt.value == f.value


def test_lsf_zone_prunes_timestamp(catalog, tmp_path):
    """Timestamp predicates skip whole LSF chunks via the int wire stats."""
    from lakesoul_tpu.io.config import IOConfig
    from lakesoul_tpu.io.lsf import LsfFile, write_lsf_table

    n = 10_000
    base = datetime.datetime(2026, 1, 1)
    tbl = pa.table(
        {
            "id": np.arange(n),
            "ts": pa.array([base + datetime.timedelta(seconds=i) for i in range(n)],
                           type=pa.timestamp("us")),
        }
    )
    path = str(tmp_path / "z.lsf")
    write_lsf_table(tbl, path, config=IOConfig(max_row_group_size=1000))
    r = LsfFile(path)
    cutoff = base + datetime.timedelta(seconds=n - 500)  # only the last chunk
    preds = [("ts", "ge", cutoff)]
    out = r.read(zone_predicates=preds)
    assert r.chunks_decoded == 1  # 9 of 10 chunks skipped undecoded
    assert out.num_rows == 1000  # chunk granularity; exact filter re-applies
    exact = out.filter(pc.field("ts") >= cutoff)
    assert exact.num_rows == 500


def test_sql_over_timestamp_and_decimal(catalog):
    from lakesoul_tpu.sql import SqlSession

    schema = pa.schema(
        [("id", pa.int64()), ("ts", pa.timestamp("us")), ("amt", pa.decimal128(10, 2))]
    )
    t = catalog.create_table("orders_tm", schema, primary_keys=["id"])
    n = 50
    base = datetime.datetime(2026, 7, 1)
    t.write_arrow(
        pa.table(
            {
                "id": np.arange(n),
                "ts": pa.array([base + datetime.timedelta(hours=i) for i in range(n)]),
                "amt": pa.array(
                    [decimal.Decimal(i) + decimal.Decimal("0.25") for i in range(n)],
                    type=pa.decimal128(10, 2),
                ),
            }
        )
    )
    sess = SqlSession(catalog)
    out = sess.execute(
        "SELECT count(*) AS c FROM orders_tm WHERE ts >= TIMESTAMP '2026-07-02 00:00:00'"
    )
    # hours 24..49 → 26 rows
    assert out.column("c").to_pylist() == [26]
    out = sess.execute("SELECT count(*) AS c FROM orders_tm WHERE amt > 40.00")
    assert out.column("c").to_pylist() == [10]  # 40.25..49.25
