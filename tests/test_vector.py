"""Vector index tests: rotation orthogonality, RaBitQ estimation quality,
kernel differential (pallas-interpret vs jnp), IVF recall, manifests, delta
inserts, and table-level e2e ANN search."""

import numpy as np
import pyarrow as pa
import pytest

from lakesoul_tpu.errors import VectorIndexError
from lakesoul_tpu.vector import IvfRabitqIndex, SearchParams, VectorIndexConfig
from lakesoul_tpu.vector.kernels import bruteforce_topk, packed_scan
from lakesoul_tpu.vector.kmeans import kmeans
from lakesoul_tpu.vector.manifest import ManifestStore
from lakesoul_tpu.vector.rabitq import RabitqQuantizer, Rotator, pack_bits, unpack_bits_jnp


def brute_force_knn(vectors, query, k):
    d = np.sum((vectors - query[None, :]) ** 2, axis=1)
    return np.argsort(d)[:k]


class TestConfig:
    def test_parse_round_trip(self):
        c = VectorIndexConfig.parse("emb:128:32:1:l2:fht:7:true")
        assert c.column == "emb" and c.dim == 128 and c.nlist == 32
        assert c.seed == 7 and c.faster is True
        assert VectorIndexConfig.parse(c.encode()) == c

    def test_parse_multiple_and_errors(self):
        cs = VectorIndexConfig.parse_multiple("a:8;b:16:4")
        assert [c.column for c in cs] == ["a", "b"]
        with pytest.raises(VectorIndexError):
            VectorIndexConfig.parse("bad")
        with pytest.raises(VectorIndexError):
            VectorIndexConfig(column="x", dim=8, total_bits=99)


class TestRotation:
    @pytest.mark.parametrize("kind", ["fht", "matrix"])
    def test_preserves_norms_and_dots(self, kind):
        rot = Rotator(48, kind, seed=3)
        rng = np.random.default_rng(0)
        x = rng.normal(size=(10, 48)).astype(np.float32)
        rx = np.asarray(rot(x))
        np.testing.assert_allclose(
            np.linalg.norm(rx, axis=1), np.linalg.norm(x, axis=1), rtol=1e-4
        )
        # pairwise inner products preserved (orthonormality)
        np.testing.assert_allclose(rx @ rx.T, x @ x.T, atol=1e-3)

    def test_bit_pack_round_trip(self):
        rng = np.random.default_rng(0)
        bits = (rng.random((5, 64)) > 0.5).astype(np.uint8)
        packed = pack_bits(bits)
        un = np.asarray(unpack_bits_jnp(packed, 64))
        np.testing.assert_array_equal(un, bits.astype(np.float32))


class TestEstimator:
    def test_estimates_correlate_with_true_distances(self):
        rng = np.random.default_rng(0)
        dim = 64
        quant = RabitqQuantizer(dim, rotator="fht", seed=1)
        vectors = rng.normal(size=(500, dim)).astype(np.float32)
        centroid = vectors.mean(0)
        codes, norms, factors, _cdc = quant.quantize(vectors, centroid)
        query = rng.normal(size=dim).astype(np.float32)
        q_rot = np.asarray(quant.rotate_query(query, centroid))
        est = np.asarray(
            packed_scan(codes, norms, factors, q_rot, d=quant.padded_dim, pallas=False)
        )
        true = np.sum((vectors - query[None, :]) ** 2, axis=1)
        corr = np.corrcoef(est, true)[0, 1]
        assert corr > 0.85, f"estimator correlation too low: {corr}"
        # estimates unbiased-ish: mean relative error small
        rel = np.abs(est - true) / np.maximum(true, 1e-6)
        assert np.median(rel) < 0.35

    def test_pallas_interpret_matches_jnp(self):
        # the pinned JAX has no pltpu.force_tpu_interpret_mode(); the kernel
        # wrapper plumbs pallas_call(interpret=True) instead, so the
        # differential test runs on any host
        rng = np.random.default_rng(1)
        dim = 64
        quant = RabitqQuantizer(dim, rotator="identity", seed=1)
        vectors = rng.normal(size=(100, dim)).astype(np.float32)
        centroid = np.zeros(dim, np.float32)
        codes, norms, factors, _cdc = quant.quantize(vectors, centroid)
        q_rot = rng.normal(size=dim).astype(np.float32)
        ref = np.asarray(
            packed_scan(codes, norms, factors, q_rot, d=dim, pallas=False)
        )
        got = np.asarray(
            packed_scan(
                codes, norms, factors, q_rot, d=dim, pallas=True, interpret=True
            )
        )
        np.testing.assert_allclose(got, ref, rtol=1e-4, atol=1e-3)


class TestKmeans:
    def test_separates_gaussian_blobs(self):
        rng = np.random.default_rng(0)
        blobs = np.concatenate(
            [rng.normal(loc=c * 10, size=(100, 8)) for c in range(4)]
        ).astype(np.float32)
        centroids, assign = kmeans(blobs, 4, iters=10)
        # each blob maps to exactly one cluster
        for b in range(4):
            labels = assign[b * 100 : (b + 1) * 100]
            assert len(np.unique(labels)) == 1


class TestIvfIndex:
    def _make(self, n=2000, dim=32, nlist=16, seed=0, keep_raw=True):
        rng = np.random.default_rng(seed)
        vectors = rng.normal(size=(n, dim)).astype(np.float32)
        ids = np.arange(n, dtype=np.uint64)
        cfg = VectorIndexConfig(column="emb", dim=dim, nlist=nlist)
        return IvfRabitqIndex.train(vectors, ids, cfg, keep_raw=keep_raw), vectors, ids

    def test_recall_at_10(self):
        index, vectors, ids = self._make()
        rng = np.random.default_rng(42)
        recalls = []
        for _ in range(20):
            q = rng.normal(size=vectors.shape[1]).astype(np.float32)
            true = set(brute_force_knn(vectors, q, 10))
            got, _ = index.search(q, SearchParams(top_k=10, nprobe=8))
            recalls.append(len(true & set(int(i) for i in got)) / 10)
        assert np.mean(recalls) >= 0.5, f"recall@10 = {np.mean(recalls)}"

    def test_rerank_depth_lifts_recall(self):
        # deeper exact-rerank shortlist → recall monotone (within noise):
        # the estimator only has to land true neighbors in the top-S, so
        # growing S recovers everything probe coverage allows
        index, vectors, _ = self._make()
        rng = np.random.default_rng(7)
        queries = rng.normal(size=(20, vectors.shape[1])).astype(np.float32)
        means = []
        for depth in (10, 200):
            recalls = []
            for q in queries:
                true = set(brute_force_knn(vectors, q, 10))
                got, _ = index.search(
                    q, SearchParams(top_k=10, nprobe=16, rerank_depth=depth)
                )
                recalls.append(len(true & set(int(i) for i in got)) / 10)
            means.append(np.mean(recalls))
        assert means[1] >= means[0]
        assert means[1] >= 0.8, f"recall@10 depth=200: {means[1]}"

    def test_recall_no_rerank_still_useful(self):
        # 1-bit codes alone on iid Gaussian data (worst case: zero cluster
        # structure) — far above chance (10/2000) but well below the reranked
        # path; the reference reaches higher via multi-bit ex-codes, which is
        # future work (total_bits > 1)
        index, vectors, _ = self._make(keep_raw=False)
        rng = np.random.default_rng(1)
        recalls = []
        for _ in range(20):
            q = rng.normal(size=vectors.shape[1]).astype(np.float32)
            true = set(brute_force_knn(vectors, q, 10))
            got, _ = index.search(q, SearchParams(top_k=10, nprobe=8))
            recalls.append(len(true & set(int(i) for i in got)) / 10)
        assert np.mean(recalls) >= 0.25

    def test_search_filtered(self):
        index, vectors, ids = self._make()
        q = vectors[7]
        allowed = np.asarray([7, 8, 9], dtype=np.uint64)
        got, dists = index.search_filtered(q, allowed, SearchParams(top_k=3, nprobe=16))
        assert set(int(i) for i in got) <= {7, 8, 9}
        assert int(got[0]) == 7  # the vector itself is nearest

    def test_insert_batch_and_merge_deltas(self):
        index, vectors, _ = self._make(n=500)
        rng = np.random.default_rng(5)
        new = rng.normal(size=(100, vectors.shape[1])).astype(np.float32)
        new_ids = np.arange(10_000, 10_100, dtype=np.uint64)
        index.insert_batch(new, new_ids)
        assert index.num_vectors == 600
        got, _ = index.search(new[3], SearchParams(top_k=1, nprobe=16))
        assert int(got[0]) == 10_003  # delta segment searched
        index.merge_deltas()
        assert index.num_vectors == 600
        got2, _ = index.search(new[3], SearchParams(top_k=1, nprobe=16))
        assert int(got2[0]) == 10_003

    def test_batch_search(self):
        index, vectors, _ = self._make(n=300)
        ids_list, dists_list = index.batch_search(vectors[:5], SearchParams(top_k=1, nprobe=16))
        hits = sum(int(ids_list[i][0]) == i for i in range(5))
        assert hits >= 4


class TestManifest:
    def test_write_read_round_trip(self, tmp_path):
        rng = np.random.default_rng(0)
        vectors = rng.normal(size=(200, 16)).astype(np.float32)
        cfg = VectorIndexConfig(column="emb", dim=16, nlist=4)
        index = IvfRabitqIndex.train(vectors, np.arange(200, dtype=np.uint64), cfg)
        index.insert_batch(vectors[:10] + 0.01, np.arange(900, 910, dtype=np.uint64))
        store = ManifestStore(str(tmp_path / "idx"))
        gen = store.write_index(index)
        assert gen == 1
        loaded = store.read_latest()
        assert loaded.num_vectors == index.num_vectors
        q = vectors[3]
        got1, _ = index.search(q, SearchParams(top_k=5, nprobe=4))
        got2, _ = loaded.search(q, SearchParams(top_k=5, nprobe=4))
        np.testing.assert_array_equal(got1, got2)
        # second write bumps the generation
        assert store.write_index(loaded) == 2

    def test_crc_detects_corruption(self, tmp_path):
        rng = np.random.default_rng(0)
        cfg = VectorIndexConfig(column="emb", dim=8, nlist=2)
        index = IvfRabitqIndex.train(
            rng.normal(size=(50, 8)).astype(np.float32),
            np.arange(50, dtype=np.uint64),
            cfg,
        )
        store = ManifestStore(str(tmp_path / "idx"))
        store.write_index(index)
        latest = tmp_path / "idx" / "LATEST"
        blob = bytearray(latest.read_bytes())
        blob[-1] ^= 0xFF
        latest.write_bytes(bytes(blob))
        with pytest.raises(VectorIndexError, match="CRC"):
            store.read_latest()


class TestExtractVectors:
    def test_null_rows_raise_typed_naming_column(self):
        from lakesoul_tpu.vector.builder import extract_vectors

        dim = 4
        table = pa.table({
            "id": pa.array([1, 2, 3], pa.int64()),
            "emb": pa.array([[1.0] * dim, None, [3.0] * dim],
                            pa.list_(pa.float32())),
        })
        # a null row would silently misalign col.values against ids
        with pytest.raises(VectorIndexError, match="'emb'.*null"):
            extract_vectors(table, "emb", "id", dim)

    def test_null_fixed_size_list_raises_too(self):
        from lakesoul_tpu.vector.builder import extract_vectors

        dim = 2
        arr = pa.FixedSizeListArray.from_arrays(
            pa.array([1.0, 2.0, 3.0, 4.0], pa.float32()), dim
        )
        table = pa.table({
            "id": pa.array([1, 2], pa.int64()),
            "emb": arr.take(pa.array([0, None], pa.int32())),
        })
        with pytest.raises(VectorIndexError, match="null"):
            extract_vectors(table, "emb", "id", dim)

    def test_clean_column_round_trips(self):
        from lakesoul_tpu.vector.builder import extract_vectors

        dim = 3
        vals = np.arange(12, dtype=np.float32).reshape(4, dim)
        table = pa.table({
            "id": pa.array(np.arange(4), pa.int64()),
            "emb": pa.FixedSizeListArray.from_arrays(vals.reshape(-1), dim),
        })
        v, i = extract_vectors(table, "emb", "id", dim)
        np.testing.assert_array_equal(v, vals)
        np.testing.assert_array_equal(i, np.arange(4))


class TestTableIntegration:
    def test_e2e_build_and_search(self, tmp_warehouse):
        from lakesoul_tpu import LakeSoulCatalog

        dim = 16
        schema = pa.schema(
            [("id", pa.int64()), ("emb", pa.list_(pa.float32(), dim)), ("tag", pa.string())]
        )
        cat = LakeSoulCatalog(str(tmp_warehouse))
        t = cat.create_table("vecs", schema, primary_keys=["id"], hash_bucket_num=2)
        rng = np.random.default_rng(0)
        n = 600
        vecs = rng.normal(size=(n, dim)).astype(np.float32)
        t.write_arrow(
            pa.table(
                {
                    "id": np.arange(n),
                    "emb": pa.FixedSizeListArray.from_arrays(vecs.reshape(-1), dim),
                    "tag": ["x"] * n,
                },
                schema=schema,
            )
        )
        total = t.build_vector_index("emb", nlist=8)
        assert total == n
        q = vecs[123]
        ids, dists = t.vector_search("emb", q, top_k=5, nprobe=8)
        assert int(ids[0]) == 123
        # ANN-filtered scan returns the actual rows through the MOR path
        rows = t.scan().vector_search("emb", q, top_k=5, nprobe=8).to_arrow()
        assert 123 in rows.column("id").to_pylist()
        assert rows.num_rows <= 5


class TestDeviceResidentCache:
    def test_resident_matches_default_path(self):
        rng = np.random.default_rng(0)
        vecs = rng.normal(size=(1500, 32)).astype(np.float32)
        cfg = VectorIndexConfig(column="e", dim=32, nlist=8)
        idx = IvfRabitqIndex.train(vecs, np.arange(1500, dtype=np.uint64), cfg)
        q = vecs[42]
        base_ids, base_d = idx.search(q, SearchParams(top_k=5, nprobe=8))
        idx.enable_device_cache()
        res_ids, res_d = idx.search(q, SearchParams(top_k=5, nprobe=8))
        np.testing.assert_array_equal(base_ids, res_ids)
        np.testing.assert_allclose(base_d, res_d, rtol=1e-5)

    def test_cache_invalidated_on_insert(self):
        rng = np.random.default_rng(1)
        vecs = rng.normal(size=(300, 16)).astype(np.float32)
        cfg = VectorIndexConfig(column="e", dim=16, nlist=4)
        idx = IvfRabitqIndex.train(vecs, np.arange(300, dtype=np.uint64), cfg)
        idx.enable_device_cache()
        idx.search(vecs[0], SearchParams(top_k=1, nprobe=4))
        idx.insert_batch(vecs[:1] + 0.001, np.array([7777], dtype=np.uint64))
        ids, _ = idx.search(vecs[0], SearchParams(top_k=2, nprobe=4))
        assert 7777 in [int(i) for i in ids]  # new delta visible post-invalidate

    def test_filtered_search_bypasses_resident_path(self):
        rng = np.random.default_rng(2)
        vecs = rng.normal(size=(200, 16)).astype(np.float32)
        cfg = VectorIndexConfig(column="e", dim=16, nlist=4)
        idx = IvfRabitqIndex.train(vecs, np.arange(200, dtype=np.uint64), cfg)
        idx.enable_device_cache()
        ids, _ = idx.search_filtered(vecs[5], np.array([5, 6], dtype=np.uint64),
                                     SearchParams(top_k=2, nprobe=4))
        assert set(int(i) for i in ids) <= {5, 6}


class TestBatchChunking:
    def test_large_batch_chunks_and_matches(self):
        rng = np.random.default_rng(0)
        vecs = rng.normal(size=(2000, 16)).astype(np.float32)
        cfg = VectorIndexConfig(column="e", dim=16, nlist=8)
        idx = IvfRabitqIndex.train(vecs, np.arange(2000, dtype=np.uint64), cfg)
        idx.enable_device_cache()
        queries = vecs[:600]  # > MAX_Q=256 → 3 chunks
        ids, dists = idx.batch_search(queries, SearchParams(top_k=3, nprobe=8))
        assert len(ids) == 600
        hits = sum(int(i in [int(x) for x in ids[i]]) for i in range(600))
        assert hits >= 590  # self-recall across chunk boundaries

    def test_relative_checkpoint_dir(self, tmp_path, monkeypatch):
        import optax
        import jax as _jax

        from lakesoul_tpu.models.checkpoint import TrainCheckpointer
        from lakesoul_tpu.models.mlp import init_mlp_params

        monkeypatch.chdir(tmp_path)
        params = init_mlp_params(_jax.random.key(0), 2)
        tx = optax.sgd(0.1)
        ck = TrainCheckpointer("rel_ckpts")  # relative path must work
        try:
            ck.save(1, params, tx.init(params))
            assert ck.latest_step() == 1
        finally:
            ck.close()


class TestExCodes:
    def _recall(self, index, vectors, n_queries=20, seed=3, nprobe=8):
        rng = np.random.default_rng(seed)
        recalls = []
        for _ in range(n_queries):
            q = rng.normal(size=vectors.shape[1]).astype(np.float32)
            true = set(brute_force_knn(vectors, q, 10))
            got, _ = index.search(q, SearchParams(top_k=10, nprobe=nprobe))
            recalls.append(len(true & set(int(i) for i in got)) / 10)
        return float(np.mean(recalls))

    def test_ex_codes_beat_one_bit_without_rerank(self):
        rng = np.random.default_rng(0)
        vectors = rng.normal(size=(2000, 32)).astype(np.float32)
        ids = np.arange(2000, dtype=np.uint64)
        r1 = self._recall(
            IvfRabitqIndex.train(
                vectors, ids, VectorIndexConfig(column="e", dim=32, nlist=16),
                keep_raw=False,
            ),
            vectors,
        )
        r8 = self._recall(
            IvfRabitqIndex.train(
                vectors, ids,
                VectorIndexConfig(column="e", dim=32, nlist=16, total_bits=8),
                keep_raw=False,
            ),
            vectors,
        )
        assert r8 > r1 + 0.2, f"1-bit {r1} vs 8-bit {r8}"
        assert r8 >= 0.8

    def test_ex_codes_with_rerank_and_inserts(self):
        rng = np.random.default_rng(1)
        vectors = rng.normal(size=(1000, 24)).astype(np.float32)
        cfg = VectorIndexConfig(column="e", dim=24, nlist=8, total_bits=4)
        idx = IvfRabitqIndex.train(vectors, np.arange(1000, dtype=np.uint64), cfg)
        got, _ = idx.search(vectors[7], SearchParams(top_k=1, nprobe=8))
        assert int(got[0]) == 7
        idx.insert_batch(vectors[:2] + 0.001, np.array([5001, 5002], dtype=np.uint64))
        got, _ = idx.search(vectors[0], SearchParams(top_k=2, nprobe=8))
        assert {int(i) for i in got} == {0, 5001}
        idx.merge_deltas()
        got2, _ = idx.search(vectors[0], SearchParams(top_k=2, nprobe=8))
        assert {int(i) for i in got2} == {0, 5001}

    def test_ex_codes_manifest_round_trip(self, tmp_path):
        rng = np.random.default_rng(2)
        vectors = rng.normal(size=(300, 16)).astype(np.float32)
        cfg = VectorIndexConfig(column="e", dim=16, nlist=4, total_bits=6)
        idx = IvfRabitqIndex.train(vectors, np.arange(300, dtype=np.uint64), cfg)
        store = ManifestStore(str(tmp_path / "exidx"))
        store.write_index(idx)
        loaded = store.read_latest()
        q = vectors[11]
        a, _ = idx.search(q, SearchParams(top_k=5, nprobe=4))
        b, _ = loaded.search(q, SearchParams(top_k=5, nprobe=4))
        np.testing.assert_array_equal(a, b)


class TestWideExCodes:
    """9-16-bit ex-codes (VERDICT r1 #8): int16 code plane, monotone recall,
    manifest round-trip, and the single-query resident path."""

    def _recall(self, index, vectors, n_queries=20, seed=3, nprobe=8):
        rng = np.random.default_rng(seed)
        recalls = []
        for _ in range(n_queries):
            q = rng.normal(size=vectors.shape[1]).astype(np.float32)
            true = set(brute_force_knn(vectors, q, 10))
            got, _ = index.search(q, SearchParams(top_k=10, nprobe=nprobe))
            recalls.append(len(true & set(int(i) for i in got)) / 10)
        return float(np.mean(recalls))

    def test_recall_monotone_8_12_16(self):
        rng = np.random.default_rng(6)
        vectors = rng.normal(size=(1500, 32)).astype(np.float32)
        ids = np.arange(1500, dtype=np.uint64)
        rs = {}
        for bits in (8, 12, 16):
            idx = IvfRabitqIndex.train(
                vectors, ids,
                VectorIndexConfig(column="e", dim=32, nlist=12, total_bits=bits),
                keep_raw=False,
            )
            assert idx.clusters[0].codes.dtype == (np.int8 if bits <= 8 else np.int16)
            rs[bits] = self._recall(idx, vectors)
        # wider codes must not regress (quantization error only shrinks)
        assert rs[12] >= rs[8] - 0.02, rs
        assert rs[16] >= rs[12] - 0.02, rs
        assert rs[16] >= 0.8, rs

    def test_wide_codes_manifest_round_trip(self, tmp_path):
        rng = np.random.default_rng(7)
        vectors = rng.normal(size=(300, 16)).astype(np.float32)
        cfg = VectorIndexConfig(column="e", dim=16, nlist=4, total_bits=12)
        idx = IvfRabitqIndex.train(vectors, np.arange(300, dtype=np.uint64), cfg)
        store = ManifestStore(str(tmp_path / "wide"))
        store.write_index(idx)
        loaded = store.read_latest()
        assert loaded.clusters[0].codes.dtype == np.int16
        q = vectors[11]
        a, _ = idx.search(q, SearchParams(top_k=5, nprobe=4))
        b, _ = loaded.search(q, SearchParams(top_k=5, nprobe=4))
        np.testing.assert_array_equal(a, b)

    def test_single_query_uses_resident_ex_path(self):
        rng = np.random.default_rng(8)
        vecs = rng.normal(size=(600, 16)).astype(np.float32)
        cfg = VectorIndexConfig(column="e", dim=16, nlist=4, total_bits=8)
        idx = IvfRabitqIndex.train(vecs, np.arange(600, dtype=np.uint64), cfg)
        idx.enable_device_cache()
        ids, dists = idx.search(vecs[3], SearchParams(top_k=3, nprobe=4))
        assert int(ids[0]) == 3
        assert idx._device_bundle is not None  # the resident bundle was built
        # matches the non-resident answer
        idx2 = IvfRabitqIndex.train(vecs, np.arange(600, dtype=np.uint64), cfg)
        ids2, _ = idx2.search(vecs[3], SearchParams(top_k=3, nprobe=4))
        assert [int(i) for i in ids] == [int(i) for i in ids2]


class TestExCodeGuards:
    def test_batch_search_ex_bits_uses_ex_resident_kernel(self):
        rng = np.random.default_rng(4)
        vecs = rng.normal(size=(500, 16)).astype(np.float32)
        cfg = VectorIndexConfig(column="e", dim=16, nlist=4, total_bits=8)
        idx = IvfRabitqIndex.train(vecs, np.arange(500, dtype=np.uint64), cfg)
        idx.enable_device_cache()  # int8 codes must hit the ex kernel, not the bit unpack
        ids, _ = idx.batch_search(vecs[:5], SearchParams(top_k=1, nprobe=4))
        assert [int(ids[i][0]) for i in range(5)] == [0, 1, 2, 3, 4]

    def test_legacy_manifest_without_scales_downgrades(self, tmp_path):
        # simulate a shard written by the pre-ex-code version: config says
        # total_bits=8 but segments carry packed 1-bit codes and no scales
        rng = np.random.default_rng(5)
        vecs = rng.normal(size=(200, 16)).astype(np.float32)
        one_bit = IvfRabitqIndex.train(
            vecs, np.arange(200, dtype=np.uint64),
            VectorIndexConfig(column="e", dim=16, nlist=4),
        )
        import dataclasses

        one_bit.config = dataclasses.replace(one_bit.config, total_bits=8)
        store = ManifestStore(str(tmp_path / "legacy"))
        store.write_index(one_bit)
        loaded = store.read_latest()
        assert loaded.config.total_bits == 1  # downgraded, searchable
        ids, _ = loaded.search(vecs[3], SearchParams(top_k=1, nprobe=4))
        assert int(ids[0]) == 3


class TestIncrementalIndexRefresh:
    def test_refresh_only_ingests_new_files(self, tmp_warehouse):
        from lakesoul_tpu import LakeSoulCatalog

        dim = 16
        schema = pa.schema([("id", pa.int64()), ("emb", pa.list_(pa.float32(), dim))])
        cat = LakeSoulCatalog(str(tmp_warehouse))
        t = cat.create_table("v", schema, primary_keys=["id"], hash_bucket_num=1)
        rng = np.random.default_rng(0)
        vecs = rng.normal(size=(400, dim)).astype(np.float32)
        t.write_arrow(pa.table({"id": np.arange(400),
                                "emb": pa.FixedSizeListArray.from_arrays(vecs.reshape(-1), dim)},
                               schema=schema))
        assert t.build_vector_index("emb", nlist=8) == 400
        # no new data → refresh is a no-op
        assert t.build_vector_index("emb", nlist=8, incremental=True) == 0
        # new commit → only the delta is indexed
        new = rng.normal(size=(50, dim)).astype(np.float32)
        t.write_arrow(pa.table({"id": np.arange(1000, 1050),
                                "emb": pa.FixedSizeListArray.from_arrays(new.reshape(-1), dim)},
                               schema=schema))
        assert t.build_vector_index("emb", nlist=8, incremental=True) == 50
        ids, _ = t.vector_search("emb", new[7], top_k=1, nprobe=8)
        assert int(ids[0]) == 1007  # delta-inserted vector findable
        ids2, _ = t.vector_search("emb", vecs[3], top_k=1, nprobe=8)
        assert int(ids2[0]) == 3    # original base still findable


class TestIncrementalAfterCompaction:
    def test_refresh_after_compact_rebuilds(self, tmp_warehouse):
        from lakesoul_tpu import LakeSoulCatalog

        dim = 16
        schema = pa.schema([("id", pa.int64()), ("emb", pa.list_(pa.float32(), dim))])
        cat = LakeSoulCatalog(str(tmp_warehouse))
        t = cat.create_table("v", schema, primary_keys=["id"], hash_bucket_num=1)
        rng = np.random.default_rng(0)
        vecs = rng.normal(size=(100, dim)).astype(np.float32)
        t.write_arrow(pa.table({"id": np.arange(100),
                                "emb": pa.FixedSizeListArray.from_arrays(vecs.reshape(-1), dim)},
                               schema=schema))
        t.build_vector_index("emb", nlist=4)
        more = rng.normal(size=(50, dim)).astype(np.float32)
        t.write_arrow(pa.table({"id": np.arange(500, 550),
                                "emb": pa.FixedSizeListArray.from_arrays(more.reshape(-1), dim)},
                               schema=schema))
        t.compact()
        # compaction rewrote the files: refresh must rebuild, not duplicate
        t.build_vector_index("emb", nlist=4, incremental=True)
        ids, _ = t.vector_search("emb", vecs[3], top_k=5, nprobe=4)
        assert len(set(int(i) for i in ids)) == len(ids)  # no duplicate ids
        assert int(ids[0]) == 3


class TestExResidentBatch:
    def test_ex_resident_batch_matches_default(self):
        rng = np.random.default_rng(0)
        vecs = rng.normal(size=(1200, 24)).astype(np.float32)
        cfg = VectorIndexConfig(column="e", dim=24, nlist=8, total_bits=8)
        idx = IvfRabitqIndex.train(vecs, np.arange(1200, dtype=np.uint64), cfg)
        queries = vecs[:16]
        base_ids, base_d = idx.batch_search(queries, SearchParams(top_k=5, nprobe=8))
        idx.enable_device_cache()
        res_ids, res_d = idx.batch_search(queries, SearchParams(top_k=5, nprobe=8))
        for a, b, da, db in zip(base_ids, res_ids, base_d, res_d):
            np.testing.assert_array_equal(a, b)
            np.testing.assert_allclose(da, db, rtol=1e-4, atol=1e-4)

    def test_ex_single_query_still_uses_nonresident(self):
        # single-query ex search keeps the per-query path (no resident single
        # kernel for ex yet); results must be correct either way
        rng = np.random.default_rng(1)
        vecs = rng.normal(size=(400, 16)).astype(np.float32)
        cfg = VectorIndexConfig(column="e", dim=16, nlist=4, total_bits=4)
        idx = IvfRabitqIndex.train(vecs, np.arange(400, dtype=np.uint64), cfg)
        idx.enable_device_cache()
        ids, _ = idx.search(vecs[9], SearchParams(top_k=1, nprobe=4))
        assert int(ids[0]) == 9


class TestStreamingShardBuild:
    def test_oversized_shard_two_pass_build(self, tmp_path):
        """train_sample_rows below the shard size forces the reservoir-train
        + second-pass-insert path; every vector must land and self-recall
        must hold."""
        from lakesoul_tpu.vector.builder import VectorShardIndexBuilder
        from lakesoul_tpu.vector.manifest import ManifestStore
        from lakesoul_tpu import LakeSoulCatalog

        catalog = LakeSoulCatalog(str(tmp_path / "wh"))
        dim = 16
        schema = pa.schema(
            [("id", pa.int64()), ("emb", pa.list_(pa.float32(), dim))]
        )
        t = catalog.create_table("vs", schema, primary_keys=["id"], hash_bucket_num=1)
        rng = np.random.default_rng(0)
        n = 3000
        vecs = rng.normal(size=(n, dim)).astype(np.float32)
        t.write_arrow(pa.table({
            "id": np.arange(n, dtype=np.int64),
            "emb": pa.FixedSizeListArray.from_arrays(vecs.reshape(-1), dim),
        }))
        cfg = VectorIndexConfig(column="emb", dim=dim, nlist=8)
        builder = VectorShardIndexBuilder(
            t.info.table_path, cfg, "id",
            train_sample_rows=500,  # << n → two-pass path
            batch_size=256,
        )
        unit = t.scan().scan_plan()[0]
        total = builder.build(unit, t.schema)
        assert total == n
        from lakesoul_tpu.vector.builder import _shard_root

        store = ManifestStore(_shard_root(t.info.table_path, "emb", unit.partition_desc,
                                          unit.bucket_id))
        index = store.read_latest()
        assert index.num_vectors == n  # pass 2 inserted EVERY vector once
        hits = 0
        for i in rng.choice(n, 50, replace=False):
            ids, _ = index.search(vecs[i], SearchParams(top_k=1, nprobe=8))
            hits += int(ids[0]) == i
        assert hits >= 45  # self-recall with exact re-rank


class TestAsyncAndServing:
    def _index(self, n=1200, d=32, seed=0):
        rng = np.random.default_rng(seed)
        vecs = rng.normal(size=(n, d)).astype(np.float32)
        cfg = VectorIndexConfig(column="e", dim=d, nlist=8)
        idx = IvfRabitqIndex.train(vecs, np.arange(n, dtype=np.uint64), cfg)
        idx.enable_device_cache()
        return idx, vecs

    def test_search_async_matches_sync(self):
        idx, vecs = self._index()
        p = SearchParams(top_k=5, nprobe=8)
        resolver = idx.search_async(vecs[17], p)
        a_ids, a_d = resolver()
        s_ids, s_d = idx.search(vecs[17], p)
        np.testing.assert_array_equal(a_ids, s_ids)
        np.testing.assert_allclose(a_d, s_d, rtol=1e-4, atol=1e-4)

    def test_search_async_pipelined_order_independent(self):
        """Resolvers can be called out of dispatch order (client pipelining)."""
        idx, vecs = self._index()
        p = SearchParams(top_k=1, nprobe=8)
        resolvers = [idx.search_async(vecs[i], p) for i in range(8)]
        outs = [r() for r in reversed(resolvers)]
        for i, (ids, _) in zip(reversed(range(8)), outs):
            assert int(ids[0]) == i  # self-NN

    def test_endpoint_results_match_direct(self):
        from lakesoul_tpu.vector.serving import AnnEndpoint

        idx, vecs = self._index()
        p = SearchParams(top_k=5, nprobe=8)
        with AnnEndpoint(idx, p, max_wait_ms=1.0) as ep:
            futs = [ep.submit(vecs[i]) for i in range(32)]
            for i, f in enumerate(futs):
                ids, dists = f.result(timeout=30)
                d_ids, d_d = idx.search(vecs[i], p)
                np.testing.assert_array_equal(ids, d_ids)
                np.testing.assert_allclose(dists, d_d, rtol=1e-4, atol=1e-4)
            stats = ep.stats()
        assert stats["requests"] == 32
        assert stats["batches"] >= 1
        # registry-histogram latency quantiles surface directly in stats
        assert stats["latency_p99"] >= stats["latency_p50"] >= 0.0

    def test_endpoint_concurrent_clients(self):
        import threading

        from lakesoul_tpu.vector.serving import AnnEndpoint

        idx, vecs = self._index()
        p = SearchParams(top_k=1, nprobe=8)
        errors = []

        def client(lo):
            try:
                for i in range(lo, lo + 10):
                    ids, _ = ep.search(vecs[i], timeout=30)
                    assert int(ids[0]) == i
            except Exception as e:  # pragma: no cover - surfaced below
                errors.append(e)

        with AnnEndpoint(idx, p, max_wait_ms=2.0) as ep:
            threads = [threading.Thread(target=client, args=(lo,)) for lo in range(0, 80, 10)]
            for t in threads:
                t.start()
            for t in threads:
                t.join()
            stats = ep.stats()
        assert not errors
        assert stats["requests"] == 80
        # micro-batching actually batched concurrent clients together
        assert stats["mean_batch"] > 1.0

    def test_endpoint_close_rejects_new_work(self):
        from lakesoul_tpu.vector.serving import AnnEndpoint

        idx, vecs = self._index(n=300)
        ep = AnnEndpoint(idx, SearchParams(top_k=1, nprobe=8))
        ep.close()
        with pytest.raises(RuntimeError, match="closed"):
            ep.submit(vecs[0])


class TestNprobeAutotune:
    """tune_nprobe picks the smallest nprobe meeting a recall target on
    held-out queries (faiss-autotune role; r5)."""

    def _hard_index(self):
        from lakesoul_tpu.vector.config import VectorIndexConfig
        from lakesoul_tpu.vector.index import IvfRabitqIndex

        rng = np.random.default_rng(7)
        n, d = 20_000, 32
        centers = rng.normal(size=(256, d)).astype(np.float32)
        vectors = centers[rng.integers(0, 256, n)] + rng.normal(
            size=(n, d)
        ).astype(np.float32)
        ids = np.arange(n, dtype=np.uint64)
        cfg = VectorIndexConfig(column="emb", dim=d, nlist=64, total_bits=4)
        index = IvfRabitqIndex.train(vectors, ids, cfg, keep_raw=True)
        queries = centers[rng.integers(0, 256, 32)] + rng.normal(
            size=(32, d)
        ).astype(np.float32)
        return index, queries

    def test_monotone_and_target(self):
        index, queries = self._hard_index()
        out = index.tune_nprobe(queries, target_recall=0.9, top_k=10)
        assert out["target_met"]
        assert 1 <= out["nprobe"] <= 64
        recalls = [r for _, r in out["measured"]]
        # sweep stops at the first qualifying nprobe (smallest wins)
        assert recalls[-1] >= 0.9
        assert all(b >= a - 0.05 for a, b in zip(recalls, recalls[1:]))

    def test_unreachable_target_reports_honestly(self):
        index, queries = self._hard_index()
        out = index.tune_nprobe(
            queries, target_recall=1.01, top_k=10  # impossible by design
        )
        assert not out["target_met"]
        assert out["nprobe"] == 64  # fell back to the deepest sweep point

    def test_requires_raw(self):
        from lakesoul_tpu.errors import ConfigError
        from lakesoul_tpu.vector.config import VectorIndexConfig
        from lakesoul_tpu.vector.index import IvfRabitqIndex

        rng = np.random.default_rng(0)
        v = rng.normal(size=(500, 16)).astype(np.float32)
        cfg = VectorIndexConfig(column="emb", dim=16, nlist=8, total_bits=4)
        index = IvfRabitqIndex.train(
            v, np.arange(500, dtype=np.uint64), cfg, keep_raw=False
        )
        with pytest.raises(ConfigError, match="keep_raw"):
            index.tune_nprobe(v[:8])
