#!/usr/bin/env python
"""One-command TPU re-validation (ROADMAP item 5).

Runs the tensorplane smoke register (``lakesoul_tpu/tensorplane/smoke.py``)
— every Pallas kernel in the repo (enumerated from lakelint's device index,
so coverage is machine-checked), the multichip shapes, and the tensorplane
delivery/replay paths — and prints one JSON report:

    python tools/tpu_smoke.py                 # report to stdout
    python tools/tpu_smoke.py --out smoke.json
    python tools/tpu_smoke.py --heavy         # run the model dryruns on CPU too

On a reachable TPU every case compiles and runs ON CHIP with per-case
pass/fail + wall seconds.  On CPU fallback every kernel still runs in
Pallas interpret mode against its jnp twin, and the report carries the
complete ``untested_on_tpu: [...]`` list — the to-do a live-tunnel session
burns down with this exact command, zero hand work.

Exit status: 0 when every executed case passed AND the register covers
100% of the enumerated Pallas kernels; 1 otherwise (an unregistered kernel
is a failure — on-chip claims must not silently fall out of the sweep).
"""

from __future__ import annotations

import argparse
import json
import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)


def main(argv: list[str] | None = None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--out", help="also write the JSON report to this path")
    ap.add_argument(
        "--heavy", action="store_true",
        help="run heavy cases (parallel model dryruns) even on CPU fallback",
    )
    args = ap.parse_args(argv)

    from lakesoul_tpu.tensorplane.smoke import run_smoke

    report = run_smoke(force_heavy=args.heavy)
    payload = json.dumps(report, indent=2)
    print(payload)
    if args.out:
        with open(args.out, "w", encoding="utf-8") as f:
            f.write(payload + "\n")
    if not report["ok"]:
        uncovered = report["kernel_enumeration"]["uncovered"]
        if uncovered:
            print(
                f"FAIL: {len(uncovered)} Pallas kernel(s) not in the smoke"
                f" register: {uncovered}", file=sys.stderr,
            )
        failed = [c["name"] for c in report["cases"] if c["status"] == "fail"]
        if failed:
            print(f"FAIL: cases failed: {failed}", file=sys.stderr)
        return 1
    if not report["on_tpu"]:
        print(
            f"note: CPU fallback — {len(report['untested_on_tpu'])} on-chip"
            " claims recorded in untested_on_tpu; rerun on a TPU host to"
            " clear them", file=sys.stderr,
        )
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
